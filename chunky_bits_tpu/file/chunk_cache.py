"""Content-addressed read cache for the serve path.

The reference has no read-side caching at all — every GET re-fetches,
re-verifies and (when degraded) re-decodes each chunk
(src/file/file_part.rs:73-135).  This module is a TPU-repo extension:
a bounded, byte-budgeted LRU keyed by the chunk's SHA-256 digest.
Because chunks are content-addressed, a digest fully identifies the
bytes, so a hit legitimately skips the network/disk fetch *and* the
hash verification — the two costs that dominate a warm read on a small
host (memory-access behavior, not GF arithmetic, dominates erasure
coding once kernels are tuned; arXiv:2108.02692).

Invariants:

- **Verified buffers only.**  The fetch path inserts only after
  ``AnyHash.verify`` passed; any other producer (e.g. RS-reconstructed
  rows) must go through :meth:`insert_verified`, which re-hashes and
  rejects a mismatch — a corrupted buffer can never enter the cache.
- **Whole chunks only.**  Range/seek trimming happens downstream
  (``FileReadBuilder`` slices, the gateway serves the slice), so a
  ranged GET both fills and is served by the same whole-chunk entries.
- **Single event loop.**  Instances are per-event-loop (the cluster
  hands them out the way it does encode batchers); all bookkeeping runs
  on the loop thread, so there are no locks.

Singleflight: N concurrent readers of one digest trigger ONE fetch; the
losers await the winner's verified buffer.  A winner that dies (error or
cancellation) does not doom the waiters — they retry, and one of them
becomes the new winner.
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from chunky_bits_tpu.file.hashing import AnyHash


@dataclass
class CacheStats:
    """Counter snapshot surfaced through ``file/profiler.py``."""

    hits: int
    misses: int
    coalesced: int
    inserts: int
    evictions: int
    rejects: int
    size_bytes: int
    capacity_bytes: int
    entries: int

    def to_obj(self) -> dict:
        """Plain-dict form (the metrics registry's cache collector and
        the ``chunky-bits stats`` renderer read this)."""
        return {
            "hits": self.hits, "misses": self.misses,
            "coalesced": self.coalesced, "inserts": self.inserts,
            "evictions": self.evictions, "rejects": self.rejects,
            "size_bytes": self.size_bytes,
            "capacity_bytes": self.capacity_bytes,
            "entries": self.entries,
        }

    def __str__(self) -> str:
        return (f"Cache<hits={self.hits} misses={self.misses} "
                f"coalesced={self.coalesced} evictions={self.evictions} "
                f"rejects={self.rejects} "
                f"bytes={self.size_bytes}/{self.capacity_bytes}>")


class _Flight:
    """One in-flight fetch.  An Event (not a Future) carries the outcome:
    a Future with an un-awaited exception would warn at GC, and waiter
    cancellation must never cancel the winner's fetch."""

    __slots__ = ("event", "result", "died")

    def __init__(self) -> None:
        self.event = asyncio.Event()
        self.result: Optional[bytes] = None  # None = all locations failed
        self.died = False  # winner raised/cancelled: waiters retry


class ChunkCache:
    """Bounded byte-budget LRU of verified chunk buffers, digest-keyed,
    with singleflight fetch deduplication."""

    #: all bookkeeping runs lock-free on the owning loop's thread (the
    #: "single event loop" invariant above); the CB204 cross-plane rule
    #: reads this tag and flags any call into the cache from
    #: HostPipeline-worker-reachable code that isn't routed through
    #: call_soon_threadsafe/run_coroutine_threadsafe
    LOOP_BOUND = True

    def __init__(self, capacity_bytes: int) -> None:
        if capacity_bytes <= 0:
            raise ValueError("capacity_bytes must be positive")
        self.capacity = int(capacity_bytes)
        self._entries: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._size = 0
        self._inflight: dict[bytes, _Flight] = {}
        self.hits = 0
        self.misses = 0  # fetches actually started (probes don't count)
        self.coalesced = 0  # waiters served by another reader's fetch
        self.inserts = 0
        self.evictions = 0
        self.rejects = 0  # corrupted pre-insert buffers refused
        # weakly self-register with the process metrics registry so a
        # /metrics scrape sees every live cache's counters (reads of
        # plain ints from the scrape thread are benign; all MUTATION
        # stays on the owning loop — the LOOP_BOUND contract holds)
        from chunky_bits_tpu.obs.metrics import get_registry

        get_registry().register_source("cache", self)

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def size_bytes(self) -> int:
        return self._size

    def contains(self, digest: bytes) -> bool:
        """Presence probe that neither counts a hit nor freshens LRU —
        for observers (the gateway access log's hit/miss tag, the
        sendfile-eligibility check) that must not skew the hit rate or
        the eviction order the serving reads establish."""
        return digest in self._entries

    def get(self, digest: bytes) -> Optional[bytes]:
        """The verified bytes for ``digest``, freshened to MRU, or None.
        A miss is not counted here — only a fetch that actually starts
        (or joins) counts, so slot-prefill probes don't skew the rate."""
        buf = self._entries.get(digest)
        if buf is None:
            return None
        self._entries.move_to_end(digest)
        self.hits += 1
        return buf

    async def get_or_fetch(
        self, digest: bytes,
        fetch: Callable[[], Awaitable[Optional[object]]],
    ) -> Optional[object]:
        """Singleflight lookup: a hit returns the cached bytes; on a miss
        exactly one caller runs ``fetch`` (which must return a VERIFIED
        buffer, or None when the chunk is unreachable) while concurrent
        callers await its outcome.  The winner's original buffer is
        returned to it (zero-copy for its own stream); waiters get the
        normalized cached bytes."""
        while True:
            buf = self.get(digest)
            if buf is not None:
                return buf
            flight = self._inflight.get(digest)
            if flight is None:
                break
            self.coalesced += 1
            # lint: unbounded-await-ok the winner sets the event in a
            # finally even on error/cancel (and `died` hands the flight
            # to a waiter), so this waits exactly as long as the
            # winner's fetch — which is itself bounded by the location
            # layer's network timeouts
            await flight.event.wait()
            if flight.died:
                continue  # winner never produced an outcome: take over
            return flight.result
        self.misses += 1
        flight = _Flight()
        self._inflight[digest] = flight
        try:
            data = await fetch()
        except BaseException:
            flight.died = True
            raise
        finally:
            self._inflight.pop(digest, None)
            flight.event.set()
        if data is not None:
            stored = self._insert(digest, data)
            # waiters get the cached bytes when stored (the one copy that
            # outlives this read); an over-budget buffer is shared as-is
            flight.result = stored if stored is not None else data
        return data

    async def insert_verified(self, hash_: AnyHash,
                              data: bytes | bytearray | memoryview
                              ) -> bool:
        """Verify-then-insert for buffers that did NOT come off a
        verified fetch (RS-reconstructed rows, pre-warming).  Re-hashes
        off-loop; a digest mismatch is rejected and counted — corrupted
        bytes never enter the cache."""
        if hash_.algorithm != "sha256" or len(data) > self.capacity:
            return False
        if not await hash_.verify_async(data):
            self.rejects += 1
            return False
        return self._insert(hash_.value.digest, data) is not None

    def _insert(self, digest: bytes, data: bytes | bytearray | memoryview
                ) -> Optional[bytes]:
        """Store ``data`` (normalized to bytes — an mmap view must not
        pin its inode for the cache's lifetime), evicting LRU entries
        past the byte budget.  Returns the stored bytes, or None when
        ``data`` alone exceeds the whole budget."""
        n = len(data)
        if n > self.capacity:
            return None
        buf = data if isinstance(data, bytes) else bytes(data)
        old = self._entries.pop(digest, None)
        if old is not None:
            self._size -= len(old)
        self._entries[digest] = buf
        self._size += n
        self.inserts += 1
        while self._size > self.capacity:
            _, evicted = self._entries.popitem(last=False)
            self._size -= len(evicted)
            self.evictions += 1
        return buf

    def stats(self) -> CacheStats:
        return CacheStats(
            hits=self.hits, misses=self.misses, coalesced=self.coalesced,
            inserts=self.inserts, evictions=self.evictions,
            rejects=self.rejects, size_bytes=self._size,
            capacity_bytes=self.capacity, entries=len(self._entries),
        )
