"""Packed slab chunk store: many chunks per file descriptor.

A TPU-repo extension beyond the reference (``Chunky-Bits`` stores one
chunk per file, src/file/location.rs:311-343): at the ROADMAP's
north-star scale (millions of objects) file-per-chunk turns filesystem
*metadata* into the bottleneck — billions of dirents, one open+stat per
chunk read, and a GC that walks every hash directory.  A slab store
packs chunks into a few large append-only files and keeps the name ->
extent mapping in its own index, so a chunk read costs one indexed
``pread`` and a GC enumeration costs one index scan.

On-disk layout, rooted at a directory::

    <root>/slab-000001.slab   append-only chunk bytes (no framing)
    <root>/index.jsonl        append-only index journal, one JSON/line
    <root>/.lock              flock target for cross-process appends

Publication protocol (the slab analogue of the local plane's
atomic-rename publication, ``location._publish_atomically``): chunk
bytes are appended to the active slab and flushed, THEN one complete
journal line ``{"o": "p", "n": <name>, "s": <slab>, "f": <offset>,
"l": <len>, "t": <unix>}`` is appended in a single write.  A chunk is
visible if and only if its journal line is written, so a crashed
*process* leaves at worst unreferenced slab tail bytes (reclaimed by
compaction) and possibly a torn final journal line (ignored by every
reader — the journal parser only consumes whole lines, and the next
append terminates the fragment).  Crash durability follows the repo's
flush-only discipline (``_publish_atomically``: flush, no fsync per
publication): after a *power loss* the page cache may persist the
journal line without the slab bytes it references, leaving a live
extent of stale/zero bytes — the same class of silent loss flush-only
rename publication accepts, except here it is content-addressed and
therefore *detectable*: every read verifies against the golden digest
and falls through/reconstructs, and the scrub daemon
(cluster/scrub.py) finds and repairs such extents without waiting for
a client read.  This window is no longer prose: every durability op
here rides the filesystem seam (``file/fsio.py``), and the
crash-consistency harness (``chunky_bits_tpu/sim/crash.py``, bench
``--config 16``) replays every crash point of the append/commit/
compact protocols — including exactly this journal-line-without-
slab-bytes power-cut image — and proves a cold restart recovers and
``scrub --once`` converges the namespace to Valid
(tests/test_crash.py).  (``compact()`` DOES fsync its data and the
store directory around its journal swap — one fsync per compaction is
cheap and makes the swap an *acknowledged*, power-loss-durable
publication; one per chunk append is not, which is the measured
tradeoff above.)  A short append (ENOSPC mid-write) truncates its
partial tail back off the slab before surfacing, so offset accounting
never packs around garbage.
Deletion appends ``{"o": "d", "n": <name>}``: the extent goes *dead*
and its bytes are reclaimed by :meth:`SlabStore.compact`, never by
punching the slab file (GC of a packed chunk must not serialize on
data I/O).

Concurrency: in-process access is serialized by a ``threading.Lock``
(sync metadata updates only — the store's methods are synchronous and
callers hop them off-loop); cross-process appenders (pre-forked gateway
workers share one store directory) serialize on ``flock(<root>/.lock)``
around the append+journal commit.  Readers take no lock: extents are
write-once (appends never rewrite published bytes) and index refresh
tolerates a torn tail.  Compaction republishes live extents into fresh
slab files and swaps the journal in by atomic rename — the same
copy-then-publish discipline as the CLI's ``migrate`` (a reader holding
an mmap view of a pre-compaction slab keeps the old inode alive, exactly
like a view across an atomic-rename republication of a chunk file).

``Location`` integration (file/location.py): ``slab:<root>/<name>``
parses to the ``slab`` kind and serves the whole existing surface —
``read``/``reader``/``read_view_mapper``/``write``/``write_shard``/
``delete``/``file_exists``/``file_len`` — so writer, resilver, gateway
and cache code need zero call-site changes to use a packed destination.
"""

from __future__ import annotations

import io
import json
import mmap
import os
import re
import threading
import time
from typing import Iterator, NamedTuple, Optional

from chunky_bits_tpu.utils import fsio as _fsio

#: rollover threshold for the active slab file; a few hundred MiB keeps
#: per-slab mmap windows and compaction copies bounded while still
#: packing ~10^5 small chunks per descriptor
DEFAULT_SLAB_MAX_BYTES = 256 << 20

JOURNAL_NAME = "index.jsonl"
LOCK_NAME = ".lock"

_SLAB_RE = re.compile(r"^slab-(\d{6})\.slab$")


class SlabExtent(NamedTuple):
    """One live chunk inside a slab file."""

    slab: str  # slab file basename
    offset: int
    length: int
    published: float  # unix time of the journal commit (GC grace)


class SlabStoreError(OSError):
    """Store-level failure surfaced to the Location plane (a subclass of
    OSError so the existing ``except OSError -> LocationError`` seams
    catch it unchanged)."""


def _parse_slab_index(name: str) -> Optional[int]:
    m = _SLAB_RE.match(name)
    return int(m.group(1)) if m else None


def _slab_name(index: int) -> str:
    return f"slab-{index:06d}.slab"


class _Flock:
    """``flock`` guard over ``<root>/.lock`` for cross-process append
    serialization; a context manager over one kept-open fd."""

    def __init__(self, root: str) -> None:
        self._path = os.path.join(root, LOCK_NAME)
        self._fd: Optional[int] = None

    def __enter__(self) -> "_Flock":
        import fcntl

        # lint: fsio-ok the flock target carries no data — creating it
        # is idempotent and crash-indifferent, so the harness has
        # nothing to record or replay here
        self._fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(self._fd, fcntl.LOCK_EX)
        except OSError:
            os.close(self._fd)
            self._fd = None
            raise
        return self

    def __exit__(self, *exc: object) -> None:
        import fcntl

        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None


class SlabStore:
    """One packed chunk store rooted at a directory.

    Every method is synchronous (bounded local file I/O) — async
    callers hop through ``asyncio.to_thread`` / the host pipeline, the
    same discipline as the one-file-per-chunk local plane.  Instances
    are process-shared per root (:func:`get_store`) so all loops and
    worker threads of a process see one coherent in-memory index.
    """

    def __init__(self, root: str,
                 slab_max_bytes: int = DEFAULT_SLAB_MAX_BYTES) -> None:
        self.root = os.path.abspath(root)
        self.slab_max_bytes = int(slab_max_bytes)
        self._lock = threading.Lock()
        self._live: dict[str, SlabExtent] = {}
        self._dead_bytes = 0
        self._journal_pos = 0  # bytes of the journal applied so far
        self._journal_id: Optional[int] = None  # st_ino of that journal
        self._loaded = False

    # ---- paths ----

    def journal_path(self) -> str:
        return os.path.join(self.root, JOURNAL_NAME)

    def slab_path(self, slab: str) -> str:
        return os.path.join(self.root, slab)

    # ---- journal loading / refresh (no lock file needed: reads
    #      tolerate a torn tail and extents are write-once) ----

    def _reset_locked(self) -> None:
        self._live.clear()
        self._dead_bytes = 0
        self._journal_pos = 0
        self._journal_id = None

    def _apply_line_locked(self, line: bytes) -> None:
        try:
            obj = json.loads(line)
        except ValueError:
            return  # foreign garbage: skip, like GC skips unknown names
        op = obj.get("o")
        name = obj.get("n")
        if not isinstance(name, str):
            return
        if op == "p":
            old = self._live.get(name)
            if old is not None:
                self._dead_bytes += old.length
            try:
                self._live[name] = SlabExtent(
                    str(obj["s"]), int(obj["f"]), int(obj["l"]),
                    float(obj.get("t", 0.0)))
            except (KeyError, TypeError, ValueError):
                return
        elif op == "d":
            old = self._live.pop(name, None)
            if old is not None:
                self._dead_bytes += old.length

    def _refresh_locked(self) -> None:
        """Apply journal bytes written since the last look (another
        process appended), or reload from scratch when the journal was
        swapped (compaction) or truncated."""
        path = self.journal_path()
        try:
            st = os.stat(path)
        except OSError:
            if self._loaded and self._journal_id is not None:
                self._reset_locked()  # journal vanished: empty store
            self._loaded = True
            return
        if (self._journal_id != st.st_ino
                or st.st_size < self._journal_pos):
            self._reset_locked()
            self._journal_id = st.st_ino
        self._loaded = True
        if st.st_size == self._journal_pos:
            return
        with open(path, "rb") as f:
            f.seek(self._journal_pos)
            tail = f.read()
        # whole lines only: a torn final line (crashed writer) stays
        # unapplied and unconsumed until its writer — or compaction —
        # completes it
        end = tail.rfind(b"\n")
        if end < 0:
            return
        for line in tail[:end].splitlines():
            self._apply_line_locked(line)
        self._journal_pos += end + 1

    # ---- lookups ----

    def lookup(self, name: str) -> Optional[SlabExtent]:
        with self._lock:
            self._refresh_locked()
            return self._live.get(name)

    def extent_path(self, name: str) -> Optional[tuple[str, int, int]]:
        """(absolute slab path, offset, length) of a live chunk — the
        gateway's zero-copy (sendfile) addressing — or None."""
        ext = self.lookup(name)
        if ext is None:
            return None
        return (self.slab_path(ext.slab), ext.offset, ext.length)

    def live_names(self) -> list[str]:
        with self._lock:
            self._refresh_locked()
            return list(self._live)

    def live_extents(self) -> list[tuple[str, SlabExtent]]:
        with self._lock:
            self._refresh_locked()
            return sorted(self._live.items())

    def live_bytes(self) -> int:
        with self._lock:
            self._refresh_locked()
            return sum(e.length for e in self._live.values())

    def dead_bytes(self) -> int:
        with self._lock:
            self._refresh_locked()
            return self._dead_bytes

    def slab_files(self) -> list[str]:
        """Basenames of the slab files currently on disk, ordered."""
        try:
            entries = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n for n in entries
                      if _parse_slab_index(n) is not None)

    # ---- reads ----

    def pread(self, name: str, start: int = 0,
              length: Optional[int] = None) -> bytes:
        """Chunk bytes (or a sub-range) by one positioned read.  Raises
        ``FileNotFoundError`` for unknown/dead names so the Location
        plane surfaces the same errno as a missing chunk file."""
        ext = self.lookup(name)
        if ext is None:
            raise FileNotFoundError(
                f"no live chunk {name!r} in slab store {self.root}")
        start = max(start, 0)
        avail = max(ext.length - start, 0)
        n = avail if length is None else max(min(length, avail), 0)
        if n == 0:
            return b""
        with open(self.slab_path(ext.slab), "rb") as f:
            f.seek(ext.offset + start)
            return f.read(n)

    def map_view(self, name: str, start: int = 0,
                 length: Optional[int] = None) -> Optional[memoryview]:
        """Zero-copy page-cache view of a live extent (or a sub-range
        inside it), or None when unmappable / out of the extent's
        bounds — mirroring ``Location.read_view_mapper``'s contract
        that the generic read path owns short-range semantics.  The
        returned view keeps its backing map alive; compaction renames
        a fresh journal in and unlinks old slabs, so a held view pins
        the old inode rather than ever observing torn bytes."""
        ext = self.lookup(name)
        if ext is None:
            return None
        if start < 0 or (length is not None and length < 0):
            return None
        end = ext.length if length is None else start + length
        if start > ext.length or end > ext.length:
            return None
        try:
            with open(self.slab_path(ext.slab), "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError, io.UnsupportedOperation):
            return None
        if ext.offset + end > len(mm):
            return None  # journal ahead of slab bytes: corrupt store
        return memoryview(mm)[ext.offset + start:ext.offset + end]

    # ---- writes ----

    def _active_slab_locked(self, incoming: int) -> tuple[str, int]:
        """(basename, current size) of the slab file the next append
        lands in, rolling over past ``slab_max_bytes``."""
        slabs = self.slab_files()
        if slabs:
            current = slabs[-1]
            try:
                size = os.path.getsize(self.slab_path(current))
            except OSError:
                size = 0
            if size + incoming <= self.slab_max_bytes or size == 0:
                return current, size
            nxt = (_parse_slab_index(current) or 0) + 1
            return _slab_name(nxt), 0
        return _slab_name(1), 0

    def _journal_append_locked(self, record: dict) -> None:
        line = (json.dumps(record, separators=(",", ":")) + "\n").encode()
        # 'a+b', not write-only: the torn-tail probe reads the last
        # byte through the same append handle (O_APPEND keeps every
        # write at EOF regardless of the probe's seek).  Unbuffered:
        # the probe must read exactly ONE byte (a buffered handle
        # would drag a full block through the filesystem per append —
        # measured 9p regression), and the line must land in one
        # write syscall like the os.write it replaces.  Seam-routed so
        # the crash harness records the commit (sim/crash.py replays a
        # crash at every point of this sequence).
        with _fsio.open(self.journal_path(), "a+b", buffering=0) as f:
            size = os.fstat(f.fileno()).st_size
            if size > 0:
                f.seek(size - 1)
                if f.read(1) != b"\n":
                    # a crashed writer left a torn final line:
                    # terminate it first so this record starts a fresh
                    # line instead of merging into (and dying with)
                    # the fragment
                    line = b"\n" + line
            f.write(line)
            if self._journal_id is None:
                self._journal_id = os.fstat(f.fileno()).st_ino
        # the caller applies this record in-memory; everything between
        # the last refresh position and the pre-append size was at most
        # the torn fragment just terminated (refresh consumed every
        # complete line under this same flock), so the applied frontier
        # is exactly the new end of file
        self._journal_pos = size + len(line)

    def append(self, name: str, data: bytes) -> SlabExtent:
        """Publish one chunk: slab append, flush, journal commit.  An
        existing live extent of the same name is superseded (it goes
        dead) — content-addressed callers normally short-circuit on
        ``file_exists`` first, and resilver's overwrite relies on the
        supersede."""
        if "/" in name or name in (".", "..", ""):
            raise SlabStoreError(f"invalid slab chunk name {name!r}")
        view = memoryview(data)
        _fsio.makedirs(self.root)
        with self._lock, _Flock(self.root):
            self._refresh_locked()
            slab, offset = self._active_slab_locked(len(view))
            path = self.slab_path(slab)
            with _fsio.open(path, "ab") as f:
                # 'ab' positions at EOF; trust the fd, not the earlier
                # stat (another writer under a different root handle
                # could have raced the rollover decision, never the
                # bytes — appends are flock-serialized)
                offset = f.tell()
                try:
                    f.write(view)
                    f.flush()
                except OSError:
                    # ENOSPC/EIO mid-append: a short write left a
                    # partial tail past `offset`.  Close (a retried
                    # flush may fail again — the bytes are already
                    # doomed) and truncate the tail away so the next
                    # append's offset accounting never packs around
                    # garbage; nothing was journaled, so the failed
                    # append is invisible to every reader
                    # (tests/test_crash.py pins this with injected
                    # short writes)
                    try:
                        f.close()
                    except OSError:
                        pass
                    try:
                        _fsio.truncate(path, offset)
                    except OSError:
                        pass  # reclaim is best-effort: the tail is
                        # unreferenced either way, just unreclaimed
                    raise
            # lint: clock-ok wall-clock publish stamp for humans (the
            # journal's `t` field is operator forensics, never a
            # duration — it must stay real even inside a simulation)
            published = time.time()
            record = {"o": "p", "n": name, "s": slab, "f": offset,
                      "l": len(view), "t": published}
            self._journal_append_locked(record)
            old = self._live.get(name)
            if old is not None:
                self._dead_bytes += old.length
            ext = SlabExtent(slab, offset, len(view), published)
            self._live[name] = ext
            return ext

    def mark_dead(self, name: str) -> None:
        """GC a chunk: the extent goes dead for compaction.  Raises
        ``FileNotFoundError`` when there is no live extent, matching
        ``os.remove`` on a missing chunk file."""
        with self._lock, _Flock(self.root):
            self._refresh_locked()
            ext = self._live.get(name)
            if ext is None:
                raise FileNotFoundError(
                    f"no live chunk {name!r} in slab store {self.root}")
            self._journal_append_locked({"o": "d", "n": name})
            del self._live[name]
            self._dead_bytes += ext.length

    # ---- compaction ----

    def compact(self) -> dict:
        """Reclaim dead extents: copy every live extent into fresh slab
        files, atomically swap in a rewritten journal (data fsync'd
        before the rename, the store directory fsync'd after it),
        unlink the old slabs.  The copy-then-publish shape of the
        CLI's ``migrate``: data lands first, the single rename makes
        it authoritative, and a crash at any point leaves a store that
        reads either entirely pre- or entirely post-compaction — the
        crash harness replays every point of this sequence under
        kill/torn/power-cut models and verifies exactly that
        (sim/crash.py ``slab_compact``, tests/test_crash.py).  A
        failing fsync aborts the swap loudly before the in-memory
        state flips.  Returns ``{"copied_bytes", "reclaimed_bytes",
        "live_chunks"}``."""
        with self._lock, _Flock(self.root):
            self._refresh_locked()
            old_slabs = self.slab_files()
            base = (_parse_slab_index(old_slabs[-1]) or 0) + 1 \
                if old_slabs else 1
            copied = 0
            out_slab = _slab_name(base)
            out_path = self.slab_path(out_slab)
            new_live: dict[str, SlabExtent] = {}
            lines: list[str] = []
            out = _fsio.open(out_path, "wb")
            try:
                for name, ext in sorted(self._live.items()):
                    if out.tell() + ext.length > self.slab_max_bytes \
                            and out.tell() > 0:
                        _fsio.fsync(out)
                        out.close()
                        base += 1
                        out_slab = _slab_name(base)
                        out_path = self.slab_path(out_slab)
                        out = _fsio.open(out_path, "wb")
                    offset = out.tell()
                    with open(self.slab_path(ext.slab), "rb") as src:
                        src.seek(ext.offset)
                        remaining = ext.length
                        while remaining > 0:
                            buf = src.read(min(remaining, 1 << 20))
                            if not buf:
                                raise SlabStoreError(
                                    f"slab {ext.slab} truncated under "
                                    f"live extent {name}")
                            out.write(buf)
                            remaining -= len(buf)
                    copied += ext.length
                    new_ext = SlabExtent(out_slab, offset, ext.length,
                                         ext.published)
                    new_live[name] = new_ext
                    lines.append(json.dumps(
                        {"o": "p", "n": name, "s": out_slab,
                         "f": offset, "l": ext.length,
                         "t": ext.published},
                        separators=(",", ":")))
                # a failing fsync here (or on the journal temp below)
                # propagates and ABORTS the swap: the old journal stays
                # authoritative, nothing is published against bytes
                # that may never have reached the platter
                # (failed-fsync poisoning — tests/test_crash.py
                # scripts it through the seam)
                _fsio.fsync(out)
            finally:
                out.close()
            if not new_live:
                # nothing live: the fresh slab is empty — drop it
                # rather than leave a zero-byte rollover target
                try:
                    _fsio.unlink(out_path)
                except OSError:
                    pass
            payload = ("".join(line + "\n" for line in lines)).encode()
            tmp = self.journal_path() + f".compact.{os.getpid()}"
            with _fsio.open(tmp, "wb") as f:
                f.write(payload)
                _fsio.fsync(f)
            _fsio.replace(tmp, self.journal_path())
            # directory-entry barrier: without it the completed rename
            # is not power-loss durable — a post-compaction power cut
            # could resurrect the old journal while later appends
            # landed against the new one (the acknowledged-write
            # durability gap the crash harness exposes; sim/crash.py's
            # powercut-meta images pin both directions).  A failure
            # raises BEFORE the in-memory state flips, so the store
            # re-reads whichever journal the disk actually holds.
            _fsio.fsync_dir(self.root)
            reclaimed = self._dead_bytes
            self._live = new_live
            self._dead_bytes = 0
            self._journal_pos = len(payload)
            self._journal_id = os.stat(self.journal_path()).st_ino
            keep = set(e.slab for e in new_live.values())
            for slab in old_slabs:
                if slab not in keep:
                    try:
                        _fsio.unlink(self.slab_path(slab))
                    except OSError:
                        pass  # still mapped elsewhere is fine; orphaned
            return {"copied_bytes": copied,
                    "reclaimed_bytes": reclaimed,
                    "live_chunks": len(new_live)}

    def stats(self) -> dict:
        with self._lock:
            self._refresh_locked()
            return {
                "root": self.root,
                "live_chunks": len(self._live),
                "live_bytes": sum(e.length for e in self._live.values()),
                "dead_bytes": self._dead_bytes,
                "slab_files": len(self.slab_files()),
            }


def is_slab_root(path: str) -> bool:
    """True when ``path`` is (or is being used as) a slab store root —
    its journal exists.  The GC uses this to pick index enumeration
    over the dirent walk."""
    return os.path.isfile(os.path.join(path, JOURNAL_NAME))


#: process-shared stores keyed by realpath.
# lint: loop-shared-ok deliberately process-wide, NOT per-loop: the
# store serializes cross-thread access with its own threading.Lock and
# cross-process access with flock, and every loop/worker of a process
# must see one coherent index per root (two instances over one root
# would race their rollover decisions)
_STORES: dict[str, SlabStore] = {}
_STORES_LOCK = threading.Lock()


def get_store(root: str) -> SlabStore:
    """The process-shared :class:`SlabStore` for a root directory."""
    key = os.path.realpath(root)
    with _STORES_LOCK:
        store = _STORES.get(key)
        if store is None:
            store = _STORES[key] = SlabStore(root)
        return store
