"""Streaming read pipeline with seek/take and part-level prefetch.

Mirrors the reference's ``FileReadBuilder`` (src/file/reader.rs): byte-range
reads (seek skips whole parts then trims the first yielded buffer,
reader.rs:39-61), default prefetch of 5 parts in flight (reader.rs:96),
``buffer_bytes`` to derive prefetch depth from a byte budget
(reader.rs:123-131), and trailing trim to the requested length.
"""

from __future__ import annotations

import asyncio
from collections import deque
from dataclasses import dataclass, field, replace
from typing import AsyncIterator, Optional

from chunky_bits_tpu.file.file_part import FilePart
from chunky_bits_tpu.file.file_reference import FileReference
from chunky_bits_tpu.file.location import LocationContext, default_context
from chunky_bits_tpu.utils import aio

DEFAULT_BUFFER = 5


@dataclass
class FileReadBuilder:
    file: FileReference
    buffer: int = DEFAULT_BUFFER
    cx: LocationContext = field(default_factory=default_context)
    seek: int = 0
    take: int = 0
    backend: Optional[str] = None  # erasure backend for reconstruction
    #: shared ReconstructBatcher (e.g. the cluster's per-loop instance,
    #: so concurrent GETs coalesce into one device dispatch); when None
    #: the stream creates — and owns closing — its own
    batcher: Optional[object] = None
    #: content-addressed read cache (file.chunk_cache.ChunkCache); hits
    #: skip fetch + verify, and whole verified chunks are what's cached
    #: even under seek/take (trimming happens here, at the edge)
    cache: Optional[object] = None
    #: host compute executor for read-side hash verification
    #: (parallel/host_pipeline.HostPipeline); None = the process-shared
    #: pipeline — the cluster serve path injects its own when
    #: ``tunables.host_threads`` pins a count
    pipeline: Optional[object] = None

    def with_backend(self, backend: Optional[str]) -> "FileReadBuilder":
        return replace(self, backend=backend)

    def with_batcher(self, batcher) -> "FileReadBuilder":
        return replace(self, batcher=batcher)

    def with_cache(self, cache) -> "FileReadBuilder":
        return replace(self, cache=cache)

    def with_pipeline(self, pipeline) -> "FileReadBuilder":
        return replace(self, pipeline=pipeline)

    def with_seek(self, seek: int) -> "FileReadBuilder":
        return replace(self, seek=seek)

    def with_take(self, take: int) -> "FileReadBuilder":
        return replace(self, take=take)

    def with_buffer(self, buffer: int) -> "FileReadBuilder":
        return replace(self, buffer=buffer)

    def location_context(self, cx: LocationContext) -> "FileReadBuilder":
        return replace(self, cx=cx)

    def buffer_bytes(self, nbytes: int) -> "FileReadBuilder":
        if self.file.parts:
            part_len = self.file.parts[0].len_bytes()
            if part_len > 0:
                buffer = (nbytes + part_len // 2) // part_len
                return replace(self, buffer=max(buffer, 1))
        return self

    def len_bytes(self) -> int:
        """Bytes this read will yield (reader.rs:133-142)."""
        length = self.file.len_bytes()
        if self.take == 0:
            return max(length - self.seek, 0)
        if length > self.seek + self.take:
            return self.take
        if length > self.seek:
            return length - self.seek
        return 0

    def file_reference(self) -> FileReference:
        return self.file

    async def stream(self) -> AsyncIterator[bytes]:
        """Yield per-chunk buffers (bytes or zero-copy page-cache views)
        with ``buffer`` parts prefetched — chunk bytes flow from storage
        to the consumer without a per-part join copy.

        The prefetched parts share one ReconstructBatcher, so a degraded
        read of many parts rebuilds its missing shards in batched device
        dispatches instead of one per part.  A builder-provided batcher
        (the cluster's per-loop shared instance) additionally coalesces
        across concurrent streams and is NOT closed here — it outlives
        any one read the way the cluster's encode batcher does."""
        from chunky_bits_tpu.ops.batching import ReconstructBatcher

        batcher = self.batcher
        owns_batcher = batcher is None
        if owns_batcher:
            batcher = ReconstructBatcher(backend=self.backend)
        remaining = self.len_bytes()
        jobs: list[tuple[FilePart, int]] = []
        seek = self.seek
        budget = remaining
        for part in self.file.parts:
            if budget <= 0:
                # parts wholly past the take window are never read: a
                # take-limited stream must not touch (or depend on the
                # health of) trailing parts the caller never asked for
                break
            part_len = part.len_bytes()
            if seek >= part_len and seek != 0:
                seek -= part_len
                continue
            jobs.append((part, seek))
            budget -= part_len - seek
            seek = 0
        tasks: deque[asyncio.Task] = deque()
        idx = 0
        try:
            while idx < len(jobs) or tasks:
                while idx < len(jobs) and len(tasks) < max(self.buffer, 1):
                    part, skip = jobs[idx]
                    tasks.append(
                        asyncio.ensure_future(
                            self._read_part(part, skip, batcher)))
                    idx += 1
                for data in await tasks.popleft():
                    if len(data) > remaining:
                        data = data[:remaining]
                    remaining -= len(data)
                    if data:
                        yield data
                    if remaining <= 0:
                        break
                if remaining <= 0:
                    break
        finally:
            for t in tasks:
                t.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
            if owns_batcher:
                await batcher.aclose()

    async def _read_part(self, part: FilePart, skip: int,
                         batcher=None) -> list:
        # backend resolution happens lazily inside part.read_buffers,
        # only when reconstruction is actually needed
        buffers = await part.read_buffers(self.cx, backend=self.backend,
                                          batcher=batcher,
                                          cache=self.cache,
                                          pipeline=self.pipeline)
        if not skip:
            return buffers
        out = []
        for buf in buffers:
            if skip >= len(buf):
                skip -= len(buf)
                continue
            out.append(buf[skip:] if skip else buf)
            skip = 0
        return out

    def reader(self) -> aio.AsyncByteReader:
        return aio.IterReader(self.stream())

    async def read_all(self) -> bytes:
        out = []
        async for chunk in self.stream():
            out.append(chunk)
        return b"".join(out)
