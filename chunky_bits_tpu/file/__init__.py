"""File codec and I/O substrate (the reference's src/file/ layer)."""

from chunky_bits_tpu.file.chunk import Chunk  # noqa: F401
from chunky_bits_tpu.file.chunk_cache import (  # noqa: F401
    CacheStats,
    ChunkCache,
)
from chunky_bits_tpu.file.collection_destination import (  # noqa: F401
    CollectionDestination,
    LocationsDestination,
    ShardWriter,
    VoidDestination,
    WeightedLocationsDestination,
)
from chunky_bits_tpu.file.file_part import (  # noqa: F401
    FileIntegrity,
    FilePart,
    LocationIntegrity,
    ResilverPartReport,
    VerifyPartReport,
    split_into_shards,
)
from chunky_bits_tpu.file.file_reference import (  # noqa: F401
    FileReference,
    ResilverFileReport,
    VerifyFileReport,
)
from chunky_bits_tpu.file.hashing import AnyHash, Sha256Hash  # noqa: F401
from chunky_bits_tpu.file.location import (  # noqa: F401
    IGNORE,
    OVERWRITE,
    Location,
    LocationContext,
    Range,
    default_context,
)
from chunky_bits_tpu.file.profiler import (  # noqa: F401
    ProfileReport,
    ProfileReporter,
    Profiler,
    new_profiler,
)
from chunky_bits_tpu.file.reader import FileReadBuilder  # noqa: F401
from chunky_bits_tpu.file.weighted_location import WeightedLocation  # noqa: F401
from chunky_bits_tpu.file.writer import FileWriteBuilder  # noqa: F401
