"""The crash-consistency harness: deterministic disk-fault injection
and recovery proof for the storage plane.

The storage-plane twin of the clock seam's scenario engine: where
``sim/scenario.py`` proves the *network/time* plane against scripted
fleet faults, this module proves the *disk* plane against every crash
point of its durability protocols.  A **mutation** (slab append +
journal commit, GC mark-dead, compaction, atomic chunk publication,
metadata publication, the repair planner's in-place rewrite) runs once
against a live directory with a :class:`RecordingFsProvider` installed
on the filesystem seam (``file/fsio.py``), capturing the exact
durability-op stream — opens with create/truncate/append flags, write
payloads, flush/fsync barriers, renames, unlinks, directory fsyncs.
The **replayer** then deterministically materializes every prefix
"crash at op k" into a cloned directory under several failure models:

* ``kill``     — process killed at op k: writes after each handle's
  last flush/fsync/close barrier die with the userspace buffer, the
  page cache (and so every flushed byte) survives.
* ``flush``    — same point, but every recorded write reached the OS
  (the buffer happened to drain): the superset-survival image.
* ``torn``     — ``flush`` with the final write cut short (1 byte and
  half-payload variants): the torn-final-write image.
* ``powercut`` — power loss: only fsync'd data is guaranteed, and the
  page cache writes back in ANY order — enumerated as per-file
  keep/drop masks over the handles with unsynced writes (the mask
  that keeps the journal line while dropping the slab bytes is
  exactly the documented ``file/slab.py`` power-loss window).
  Directory entries (renames, creates, unlinks) survive: metadata
  journaling is ordered, data writeback is not.
* ``powercut-meta`` — the other extreme: every name op after the last
  ``fsync_dir`` barrier is also lost (an un-fsync'd rename is not
  durable) along with all unsynced data.  This is the model that
  makes the directory-fsync satellite provable: a completed metadata
  publication or compaction swap must survive it, because the code
  now fsyncs the directory before returning.

After each image the **verifier** restarts the store machinery cold
(fresh ``SlabStore``, fresh ``Location``/``MetadataPath``) and asserts
the invariants the docstrings claim: pre-existing (snapshot-durable)
data always reads back byte-exact; the mutated name is absent, exact,
or — in powercut images only — present with bytes the content-address
gate DETECTS (never silently wrong); torn journal tails are ignored
and repaired by the next append; compaction leaves the old or the new
journal, never neither; acknowledged metadata publications survive
every power-cut image; the stale-temp reaper can never eat a live
store file; and the store accepts new work afterwards.
:func:`run_cluster_recovery` runs the same machinery one level up: a
real erasure-coded cluster with one destination rolled back to a crash
image, then ``scrub --once`` (the production ``ScrubDaemon`` with the
repair planner) must converge the namespace to Valid — including the
journal-line-without-slab-bytes power-loss image.

Determinism: mutations seed their payload RNG, op streams are replayed
(not re-executed), and :func:`matrix_digest` hashes the normalized op
stream plus every verdict — same seed ⇒ same crash matrix, same
verdicts (bench ``--config 16`` double-runs it; wall-clock publish
stamps are excluded from the digest by construction).

Production paths import NOTHING from this module (the ``sim/``
discipline, pinned by test); it is tooling for tests, bench and
scenario scripts.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import random
import re
import shutil
from dataclasses import dataclass, field
from typing import Callable, Optional

from chunky_bits_tpu.utils import fsio as _fsio
from chunky_bits_tpu.utils.fsio import FsOp, RecordingFsProvider

__all__ = [
    "CrashMatrixResult",
    "CrashVerdict",
    "MUTATIONS",
    "OpReplayer",
    "matrix_digest",
    "record_mutation",
    "run_cluster_recovery",
    "run_matrix",
]

#: data-barrier ops per failure model: writes after a handle's last
#: barrier are lost (powercut honors only true fsync; kill honors the
#: userspace-buffer drains too)
_KILL_BARRIERS = ("flush", "fsync", "close")
_SYNC_BARRIERS = ("fsync",)

#: cap on per-fid powercut mask enumeration: up to 3 unsynced handles
#: enumerate every subset; beyond that, all-drop / all-keep / each
#: singleton-keep (the adversarial corners) keep the matrix bounded
_MASK_EXHAUSTIVE_FIDS = 3

#: normalizers for the determinism digest: publication temps and
#: compaction temps embed pid/random hex that vary run to run while
#: naming the same logical op
_NORM_RES = (
    (re.compile(r"\.tmp\.\d+\.[0-9a-f]{8}"), ".tmp.<pid>.<rand>"),
    (re.compile(r"\.compact\.\d+"), ".compact.<pid>"),
)


def _norm_path(path: str) -> str:
    for pattern, repl in _NORM_RES:
        path = pattern.sub(repl, path)
    return path


def record_mutation(root: str, fn: Callable[[], None]) -> list[FsOp]:
    """Run ``fn`` with a :class:`RecordingFsProvider` rooted at
    ``root`` installed on the seam; returns the captured op stream.
    Ops outside ``root`` pass through unrecorded (one failure domain
    per recording)."""
    provider = RecordingFsProvider(root)
    previous = _fsio.install(provider)
    try:
        fn()
    finally:
        _fsio.install(previous)
    return list(provider.ops)


# ---- the replayer: op stream -> crash image ----

class OpReplayer:
    """Materializes crash images from a snapshot directory plus a
    recorded op stream.  The virtual filesystem is inode-accurate:
    writes bind to the handle (fid) they were issued on, so a write
    that raced a dropped rename lands on the orphaned inode — absent
    from the image — exactly as on a real disk, never blended into
    whatever file the name points at afterwards."""

    def __init__(self, snapshot: str) -> None:
        self.snapshot = os.path.abspath(snapshot)
        #: rel path -> initial bytes (inode identity starts per-name)
        self._initial: dict[str, bytes] = {}
        self._initial_dirs: list[str] = []
        for dirpath, dirnames, filenames in os.walk(self.snapshot):
            rel_dir = os.path.relpath(dirpath, self.snapshot)
            if rel_dir != ".":
                self._initial_dirs.append(rel_dir.replace(os.sep, "/"))
            for name in filenames:
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, self.snapshot)
                with open(full, "rb") as f:
                    self._initial[rel.replace(os.sep, "/")] = f.read()

    # -- survival analysis --

    @staticmethod
    def _data_barriers(ops: list[FsOp], k: int,
                       barriers: tuple[str, ...]) -> dict[int, int]:
        """fid -> index of its LAST surviving data barrier before k
        (writes after it are lost in barrier-honoring modes)."""
        last: dict[int, int] = {}
        for i in range(k):
            op = ops[i]
            if op.op in barriers and op.fid >= 0:
                last[op.fid] = i
        return last

    @staticmethod
    def _unsynced_fids(ops: list[FsOp], k: int) -> list[int]:
        """Handles with at least one write after their last fsync —
        the powercut mask domain, in first-write order."""
        last_sync = OpReplayer._data_barriers(ops, k, _SYNC_BARRIERS)
        seen: list[int] = []
        for i in range(k):
            op = ops[i]
            if op.op == "write" and i > last_sync.get(op.fid, -1) \
                    and op.fid not in seen:
                seen.append(op.fid)
        return seen

    def variants(self, ops: list[FsOp], k: int
                 ) -> list[tuple[str, str, dict]]:
        """Every (mode, variant-id, params) image to build for a crash
        before op ``k`` — the deterministic enumeration bench --config
        16 reports as its crash-point count."""
        out: list[tuple[str, str, dict]] = [
            ("kill", "", {}),
            ("flush", "", {}),
            ("powercut-meta", "", {}),
        ]
        if k > 0 and ops[k - 1].op == "write" \
                and len(ops[k - 1].data) >= 2:
            out.append(("torn", "1", {"torn": 1}))
            out.append(("torn", "half",
                        {"torn": len(ops[k - 1].data) // 2}))
        fids = self._unsynced_fids(ops, k)
        if len(fids) <= _MASK_EXHAUSTIVE_FIDS:
            masks = range(1 << len(fids))
        else:
            masks = [0, (1 << len(fids)) - 1] \
                + [1 << i for i in range(len(fids))]
        for mask in masks:
            keep = frozenset(f for i, f in enumerate(fids)
                             if mask & (1 << i))
            out.append(("powercut", f"m{mask}", {"keep": keep}))
        return out

    def build(self, ops: list[FsOp], k: int, mode: str, dest: str,
              torn: Optional[int] = None,
              keep: frozenset = frozenset()) -> None:
        """Materialize the crash image for ops[0:k] under ``mode``
        into ``dest`` (created fresh)."""
        if mode in ("flush", "torn"):
            def write_survives(i: int, op: FsOp) -> bool:
                return True
        elif mode == "kill":
            last = self._data_barriers(ops, k, _KILL_BARRIERS)

            def write_survives(i: int, op: FsOp) -> bool:
                return i <= last.get(op.fid, -1) or self._barrier_after(
                    ops, k, i, op.fid, _KILL_BARRIERS)
        else:  # powercut / powercut-meta
            def write_survives(i: int, op: FsOp) -> bool:
                if self._barrier_after(ops, k, i, op.fid,
                                       _SYNC_BARRIERS):
                    return True
                return (mode == "powercut" and op.fid in keep)
        if mode == "powercut-meta":
            last_dir_sync = -1
            for i in range(k):
                if ops[i].op == "fsync_dir":
                    last_dir_sync = i

            def name_survives(i: int) -> bool:
                return i <= last_dir_sync
        else:
            def name_survives(i: int) -> bool:
                return True

        # virtual fs: fid -> content; name -> fid; created dirs
        files: dict[int, bytearray] = {}
        names: dict[str, int] = {}
        dirs: list[str] = list(self._initial_dirs)
        next_fid = [10 ** 9]  # snapshot inode ids live above recorded
        for rel, data in self._initial.items():
            fid = next_fid[0]
            next_fid[0] += 1
            files[fid] = bytearray(data)
            names[rel] = fid
        fidmap: dict[int, int] = {}

        for i in range(k):
            op = ops[i]
            if op.op == "open":
                existing = names.get(op.path)
                if existing is not None and "t" not in op.aux:
                    fidmap[op.fid] = existing  # same inode, append/rw
                elif existing is not None and "t" in op.aux:
                    # O_TRUNC keeps the inode; the size change is
                    # metadata-journaled — honor name-survival
                    fidmap[op.fid] = existing
                    if name_survives(i):
                        files[existing] = bytearray()
                else:
                    # creation: the dirent is a name op, the inode is
                    # real either way — writes land on it, but a
                    # dropped dirent orphans the whole file
                    fidmap[op.fid] = op.fid
                    files[op.fid] = bytearray()
                    if name_survives(i):
                        names[op.path] = op.fid
            elif op.op == "write":
                fid = fidmap.get(op.fid, op.fid)
                if fid not in files:
                    files[fid] = bytearray()
                if write_survives(i, op):
                    data = op.data
                    if mode == "torn" and i == k - 1 \
                            and torn is not None:
                        data = data[:torn]
                    files[fid].extend(data)
            elif op.op == "replace":
                if name_survives(i):
                    src_fid = names.pop(op.aux, None)
                    if src_fid is not None:
                        names[op.path] = src_fid
            elif op.op == "unlink":
                if name_survives(i):
                    names.pop(op.path, None)
            elif op.op == "mkdir":
                if name_survives(i):
                    dirs.append(op.path)
            elif op.op == "truncate":
                # os.truncate by path: an i-size metadata op
                if name_survives(i):
                    fid = names.get(op.path)
                    if fid is not None:
                        del files[fid][int(op.aux):]
            # flush/fsync/close/fsync_dir: barriers, handled above

        os.makedirs(dest, exist_ok=True)
        for rel in dirs:
            os.makedirs(os.path.join(dest, rel), exist_ok=True)
        for rel, fid in names.items():
            full = os.path.join(dest, rel)
            os.makedirs(os.path.dirname(full) or dest, exist_ok=True)
            with open(full, "wb") as f:
                f.write(files.get(fid, bytearray()))

    @staticmethod
    def _barrier_after(ops: list[FsOp], k: int, i: int, fid: int,
                       barriers: tuple[str, ...]) -> bool:
        """True when a barrier for ``fid`` lands in (i, k) — the write
        at i was made durable by a LATER surviving barrier."""
        for j in range(i + 1, k):
            if ops[j].op in barriers and ops[j].fid == fid:
                return True
        return False


# ---- verdicts / matrix plumbing ----

@dataclass
class CrashVerdict:
    """One crash image's verification outcome."""

    mutation: str
    mode: str
    k: int
    variant: str
    ok: bool
    violations: list[str] = field(default_factory=list)

    def to_obj(self) -> dict:
        return {
            "mutation": self.mutation, "mode": self.mode, "k": self.k,
            "variant": self.variant, "ok": self.ok,
            **({"violations": self.violations[:4]}
               if self.violations else {}),
        }


@dataclass
class CrashMatrixResult:
    """The full matrix run: bench --config 16's row source and the
    determinism comparison unit."""

    verdicts: list[CrashVerdict]
    ops_by_mutation: dict[str, int]
    digest: str

    def ok(self) -> bool:
        return bool(self.verdicts) and all(v.ok for v in self.verdicts)

    def failed(self) -> list[CrashVerdict]:
        return [v for v in self.verdicts if not v.ok]

    def crash_points(self) -> int:
        return sum(n + 1 for n in self.ops_by_mutation.values())

    def rows(self) -> list[dict]:
        by_mut: dict[str, dict] = {}
        for v in self.verdicts:
            row = by_mut.setdefault(v.mutation, {
                "mutation": v.mutation, "images": 0, "images_ok": 0,
                "ops": self.ops_by_mutation.get(v.mutation, 0)})
            row["images"] += 1
            row["images_ok"] += int(v.ok)
        return [by_mut[name] for name in sorted(by_mut)]


def matrix_digest(ops_streams: dict[str, list[FsOp]],
                  verdicts: list[CrashVerdict]) -> str:
    """Canonical digest of the crash matrix: normalized op stream
    shape (kinds + paths, never payload bytes — journal lines embed
    wall-clock publish stamps) plus every verdict tuple.  Equal across
    same-seed runs; the determinism double-run pins it."""
    h = hashlib.sha256()
    for name in sorted(ops_streams):
        for i, op in enumerate(ops_streams[name]):
            h.update(json.dumps(
                [name, i, op.op, _norm_path(op.path),
                 _norm_path(op.aux) if op.op == "replace" else ""],
                separators=(",", ":")).encode())
    for v in verdicts:
        h.update(json.dumps(
            [v.mutation, v.mode, v.k, v.variant, v.ok],
            separators=(",", ":")).encode())
    return h.hexdigest()


# ---- the mutation library ----

def _digest_name(payload: bytes) -> str:
    """Content-addressed chunk name: the gate every read verifies."""
    return hashlib.sha256(payload).hexdigest()


def _fresh_store(root: str):
    """A COLD SlabStore over ``root`` — deliberately not
    ``slab.get_store`` (whose process cache would hand back a warm
    index and defeat the restart-from-disk contract under test)."""
    from chunky_bits_tpu.file.slab import SlabStore

    return SlabStore(root)


@dataclass(frozen=True)
class Mutation:
    """One recorded storage-plane mutation plus its recovery oracle.

    ``setup(root, rng)`` builds the durable pre-state and returns the
    oracle state; ``run(root, state)`` performs the mutation (recorded
    through the seam); ``verify(image, state, mode, k, complete)``
    returns invariant violations for one crash image (empty = clean).
    """

    name: str
    setup: Callable[[str, random.Random], dict]
    run: Callable[[str, dict], None]
    verify: Callable[[str, dict, str, int, bool], list[str]]


def _reap_temps(image: str) -> None:
    """Simulate the GC's stale-temp reaper over a crash image: every
    ``is_publish_temp`` basename goes — live store files must never
    match it (verified by re-reading afterwards)."""
    from chunky_bits_tpu.file.location import is_publish_temp

    for dirpath, _dirnames, filenames in os.walk(image):
        for fname in filenames:
            if is_publish_temp(fname):
                os.unlink(os.path.join(dirpath, fname))


def _verify_slab_image(image: str, expected: dict[str, bytes],
                       pending: Optional[tuple[str, bytes]],
                       removed: Optional[str],
                       mode: str, complete: bool) -> list[str]:
    """The shared slab-store oracle.  ``expected``: chunks durable
    before the recording (must always read exact).  ``pending``: the
    chunk the mutation was publishing (absent | exact | detectably
    damaged in powercut images).  ``removed``: the chunk the mutation
    was deleting (exact | absent)."""
    violations: list[str] = []
    try:
        store = _fresh_store(image)
        live = dict(store.live_extents())
    except Exception as err:  # noqa: BLE001 — ANY cold-load crash is
        # itself the invariant violation being hunted
        return [f"cold index load failed: {type(err).__name__}: {err}"]

    def read(name: str) -> bytes:
        try:
            return store.pread(name)
        except OSError:
            return b""

    for name, payload in expected.items():
        if removed is not None and name == removed:
            continue
        if name not in live:
            violations.append(f"durable chunk {name[:8]} lost")
        elif read(name) != payload:
            violations.append(f"durable chunk {name[:8]} wrong bytes")
    if removed is not None:
        if removed in live and read(removed) != expected[removed]:
            violations.append("half-deleted chunk serves wrong bytes")
        if complete and mode in ("kill", "flush", "torn") \
                and removed in live:
            violations.append("completed delete not visible after "
                              "process crash")
    if pending is not None:
        name, payload = pending
        if name in live:
            got = read(name)
            if got != payload:
                # wrong bytes may surface ONLY where unsynced pages
                # can vanish, and must be DETECTABLE (content address)
                if mode not in ("powercut", "powercut-meta"):
                    violations.append(
                        "published chunk torn outside powercut "
                        f"(mode={mode})")
                elif _digest_name(got) == name:
                    violations.append("content-address gate blind to "
                                      "damaged chunk")
        if complete and mode in ("kill", "flush", "torn") \
                and name not in live:
            violations.append("acknowledged append invisible after "
                              "process crash")
    extras = set(live) - set(expected) \
        - ({pending[0]} if pending else set())
    if extras:
        violations.append(f"phantom extents {sorted(extras)[:2]}")

    # the GC reaper must never eat a live store file
    _reap_temps(image)
    after_reap = _fresh_store(image)
    for name, payload in expected.items():
        if removed is not None and name == removed:
            continue
        if name in live:
            try:
                if after_reap.pread(name) != payload:
                    violations.append("stale-temp reap damaged a live "
                                      "extent")
                    break
            except OSError:
                violations.append("stale-temp reap removed a live "
                                  "extent")
                break

    # forward progress: the next append must terminate any torn
    # journal tail and serve its bytes back
    recovery_payload = b"recovery-" + os.urandom(8)
    recovery_name = _digest_name(recovery_payload)
    try:
        after_reap.append(recovery_name, recovery_payload)
    except Exception as err:  # noqa: BLE001 — ANY recovery-append
        # failure on a crash image is the finding
        violations.append(f"recovery append failed: "
                          f"{type(err).__name__}: {err}")
        return violations
    reloaded = _fresh_store(image)
    if reloaded.pread(recovery_name) != recovery_payload:
        violations.append("recovery append unreadable after reload")
    for name, payload in expected.items():
        if removed is not None and name == removed:
            continue
        if name in live and reloaded.pread(name) != payload:
            violations.append("recovery append disturbed a durable "
                              "chunk")
            break
    return violations


# -- slab append --

def _setup_slab(root: str, rng: random.Random) -> dict:
    store = _fresh_store(root)
    expected: dict[str, bytes] = {}
    for _ in range(3):
        payload = rng.randbytes(rng.randrange(200, 1500))
        name = _digest_name(payload)
        store.append(name, payload)
        expected[name] = payload
    # a dead extent gives compaction real work
    doomed = rng.randbytes(300)
    store.append(_digest_name(doomed), doomed)
    store.mark_dead(_digest_name(doomed))
    new_payload = rng.randbytes(900)
    return {"expected": expected,
            "victim": sorted(expected)[0],
            "new": (_digest_name(new_payload), new_payload)}


def _run_slab_append(root: str, state: dict) -> None:
    name, payload = state["new"]
    _fresh_store(root).append(name, payload)


def _verify_slab_append(image: str, state: dict, mode: str, k: int,
                        complete: bool) -> list[str]:
    return _verify_slab_image(image, state["expected"], state["new"],
                              None, mode, complete)


# -- slab mark-dead --

def _run_slab_mark_dead(root: str, state: dict) -> None:
    _fresh_store(root).mark_dead(state["victim"])


def _verify_slab_mark_dead(image: str, state: dict, mode: str, k: int,
                           complete: bool) -> list[str]:
    return _verify_slab_image(image, state["expected"], None,
                              state["victim"], mode, complete)


# -- slab compaction --

def _run_slab_compact(root: str, state: dict) -> None:
    _fresh_store(root).compact()


def _verify_slab_compact(image: str, state: dict, mode: str, k: int,
                         complete: bool) -> list[str]:
    violations = _verify_slab_image(image, state["expected"], None,
                                    None, mode, complete)
    # old journal or new journal, never neither: the shared oracle
    # already proved every durable chunk readable; here pin that the
    # journal FILE survived every image (a missing journal is an empty
    # store — "neither")
    if not os.path.isfile(os.path.join(image, "index.jsonl")):
        violations.append("compaction crash left no journal at all")
    # a completed compaction is an acknowledged swap: after the
    # directory fsync it must also survive both power-cut models with
    # the dead extent actually reclaimed from the index
    if complete:
        store = _fresh_store(image)
        if store.dead_bytes() != 0:
            violations.append("completed compaction rolled back "
                              f"(mode={mode}: dead bytes resurfaced)")
    return violations


# -- atomic chunk publication (the writer's shard landing) --

def _setup_publish(root: str, rng: random.Random) -> dict:
    os.makedirs(root, exist_ok=True)
    payload = rng.randbytes(1100)
    return {"target": "chunk", "old": None,
            "new": (_digest_name(payload), payload)}


def _run_publish(root: str, state: dict) -> None:
    from chunky_bits_tpu.file.location import Location

    _name, payload = state["new"]
    target = os.path.join(root, state["target"])
    asyncio.run(Location.parse(target).write(payload))


def _verify_publish(image: str, state: dict, mode: str, k: int,
                    complete: bool) -> list[str]:
    violations: list[str] = []
    name, payload = state["new"]
    old: Optional[bytes] = state["old"]
    target = os.path.join(image, state["target"])
    if os.path.exists(target):
        with open(target, "rb") as f:
            got = f.read()
        allowed = [payload] + ([old] if old is not None else [])
        if got not in allowed:
            if mode not in ("powercut", "powercut-meta"):
                violations.append(
                    f"published path torn outside powercut "
                    f"(mode={mode}, {len(got)}b)")
            elif _digest_name(got) == name:
                violations.append("content-address gate blind to "
                                  "damaged publication")
    elif old is not None:
        violations.append("pre-existing target vanished")
    elif complete and mode in ("kill", "flush", "torn"):
        violations.append("acknowledged publication invisible after "
                          "process crash")
    # crashed-writer temps must be reapable without touching the target
    _reap_temps(image)
    remaining = [f for f in os.listdir(image)]
    if state["target"] in remaining:
        with open(target, "rb") as f:
            after = f.read()
        allowed = [payload] + ([old] if old is not None else [])
        if after not in allowed \
                and mode not in ("powercut", "powercut-meta"):
            violations.append("temp reap disturbed the published path")
    stray = [f for f in remaining
             if f != state["target"] and not f.startswith(".")]
    if stray:
        violations.append(f"unreapable leftovers {stray[:2]}")
    return violations


# -- repair planner in-place rewrite --

def _setup_repair(root: str, rng: random.Random) -> dict:
    os.makedirs(root, exist_ok=True)
    payload = rng.randbytes(1100)
    corrupt = bytearray(payload)
    corrupt[rng.randrange(len(corrupt))] ^= 0x5A
    with open(os.path.join(root, "chunk"), "wb") as f:
        f.write(bytes(corrupt))
    return {"target": "chunk", "old": bytes(corrupt),
            "new": (_digest_name(payload), payload)}


def _run_repair_rewrite(root: str, state: dict) -> None:
    from chunky_bits_tpu.file.location import (
        OVERWRITE,
        Location,
        default_context,
    )

    _name, payload = state["new"]
    target = os.path.join(root, state["target"])
    # exactly the planner's write shape (cluster/repair.py
    # _write_victims): a content-verified payload overwriting the
    # victim in place through the atomic-publication protocol
    cx = default_context().but_with(on_conflict=OVERWRITE)
    asyncio.run(Location.parse(target).write(payload, cx))


# -- metadata publication --

def _setup_metadata(root: str, rng: random.Random) -> dict:
    from chunky_bits_tpu.cluster.metadata import MetadataPath

    os.makedirs(root, exist_ok=True)
    old = {"length": 1, "parts": [rng.randrange(1 << 30)]}
    asyncio.run(MetadataPath(root, None).write("obj", old))
    new = {"length": 2, "parts": [rng.randrange(1 << 30),
                                  rng.randrange(1 << 30)]}
    return {"target": "obj", "old": old, "new": new}


def _run_metadata(root: str, state: dict) -> None:
    from chunky_bits_tpu.cluster.metadata import MetadataPath

    asyncio.run(MetadataPath(root, None).write(state["target"],
                                               state["new"]))


def _verify_metadata(image: str, state: dict, mode: str, k: int,
                     complete: bool) -> list[str]:
    from chunky_bits_tpu.cluster.metadata import MetadataPath

    violations: list[str] = []
    meta = MetadataPath(image, None)

    def parsed() -> Optional[dict]:
        try:
            return asyncio.run(meta.read(state["target"]))
        except Exception:  # noqa: BLE001 — unparseable/absent is the
            # classification being tested, not an oracle failure
            return None

    got = parsed()
    if got not in (state["old"], state["new"]):
        violations.append(
            "metadata neither old nor new "
            f"({'unreadable' if got is None else 'foreign'})")
    # the acknowledged-write durability pin (the dir-fsync satellite):
    # a COMPLETED metadata publication survives every failure model,
    # including both power-cut extremes
    if complete and got != state["new"]:
        violations.append(
            f"acknowledged metadata publication lost (mode={mode})")
    # crashed-writer temps: the next write must reap them
    for fname in os.listdir(image):
        full = os.path.join(image, fname)
        if fname != state["target"]:
            os.utime(full, (1.0, 1.0))  # age past STALE_TEMP_SECONDS
    _run_metadata(image, state)
    from chunky_bits_tpu.file.location import is_publish_temp

    leaked = [f for f in os.listdir(image) if is_publish_temp(f)]
    if leaked:
        violations.append(f"stale temps not reaped on next write: "
                          f"{leaked[:2]}")
    if parsed() != state["new"]:
        violations.append("recovery write unreadable")
    return violations


# -- meta-log publication / compaction (cluster/meta_log.py) --

def _fresh_meta_store(root: str):
    """A COLD MetaLogStore over ``root`` — deliberately not
    ``meta_log.get_store`` (same rationale as ``_fresh_store``: the
    process cache would hand back a warm index and defeat the
    restart-from-disk contract under test)."""
    from chunky_bits_tpu.cluster.meta_log import MetaLogStore

    return MetaLogStore(root)


def _verify_meta_log_image(image: str, expected: dict[str, bytes],
                           pending: Optional[tuple[str, bytes]],
                           mode: str, complete: bool) -> list[str]:
    """The shared meta-log oracle — STRONGER than the slab oracle on
    the pending entry: a meta-log publish is the cluster's write
    acknowledgment (ref bytes fsync'd, then the journal line fsync'd,
    then a directory fsync when a file was created), so a COMPLETED
    append must survive EVERY failure model, both power-cut extremes
    included — and a ref the index serves at all must serve exact
    bytes in every mode (the journal line only ever lands after its
    ref bytes are on the platter, and torn journal lines are never
    applied)."""
    violations: list[str] = []
    try:
        store = _fresh_meta_store(image)
        names = set(store.live_names())
    except Exception as err:  # noqa: BLE001 — ANY cold-load crash is
        # itself the invariant violation being hunted
        return [f"cold index load failed: {type(err).__name__}: {err}"]

    def read(name: str) -> bytes:
        try:
            return store.read_bytes(name)
        except OSError:
            return b""

    for name, payload in expected.items():
        if name not in names:
            violations.append(f"durable ref {name!r} lost (mode={mode})")
        elif read(name) != payload:
            violations.append(f"durable ref {name!r} wrong bytes")
    if pending is not None:
        name, payload = pending
        if name in names and read(name) != payload:
            violations.append(
                f"indexed ref {name!r} serves wrong bytes (mode={mode}:"
                " journal committed before its data was durable)")
        if complete and name not in names:
            violations.append(
                f"acked metadata publish lost (mode={mode})")
    extras = names - set(expected) \
        - ({pending[0]} if pending else set())
    if extras:
        violations.append(f"phantom refs {sorted(extras)[:2]}")

    # forward progress: the next publish must terminate any torn
    # journal tail and serve its bytes back
    recovery_payload = b"recovery-" + os.urandom(8)
    try:
        store.append("recovery-obj", recovery_payload)
    except Exception as err:  # noqa: BLE001 — ANY recovery-append
        # failure on a crash image is the finding
        violations.append(f"recovery publish failed: "
                          f"{type(err).__name__}: {err}")
        return violations
    reloaded = _fresh_meta_store(image)
    try:
        if reloaded.read_bytes("recovery-obj") != recovery_payload:
            violations.append("recovery publish unreadable after "
                              "reload")
    except OSError:
        violations.append("recovery publish invisible after reload")
    for name, payload in expected.items():
        if name in names:
            try:
                if reloaded.read_bytes(name) != payload:
                    violations.append("recovery publish disturbed a "
                                      "durable ref")
                    break
            except OSError:
                violations.append("recovery publish lost a durable ref")
                break
    return violations


def _proj_of(name: str) -> tuple[list, list]:
    """Deterministic index projection (hashes, node keys) for a setup
    ref — publish records in the matrix carry the projection fields so
    every crash point of the LONGER journal line (and compaction's
    projection copy) is replayed too."""
    digest = "sha256-" + name.encode().hex().ljust(64, "0")[:64]
    return [digest], [["local", f"/nodes/{name.split('/')[-1]}"]]


def _setup_meta_log(root: str, rng: random.Random) -> dict:
    store = _fresh_meta_store(root)
    expected: dict[str, bytes] = {}
    for i in range(3):
        payload = rng.randbytes(rng.randrange(100, 900))
        name = f"dir/obj-{i}"
        hashes, nodes = _proj_of(name)
        store.append(name, payload, hashes=hashes, nodes=nodes)
        expected[name] = payload
    # a tombstone gives compaction real work (dead bytes + a dropped
    # record) and pins that replays keep it dead
    doomed = rng.randbytes(300)
    store.append("dir/doomed", doomed)
    store.tombstone("dir/doomed")
    new_payload = rng.randbytes(700)
    return {"expected": expected, "gen": store.generation(),
            "new": ("dir/obj-new", new_payload)}


def _run_meta_log_append(root: str, state: dict) -> None:
    name, payload = state["new"]
    hashes, nodes = _proj_of(name)
    _fresh_meta_store(root).append(name, payload,
                                   hashes=hashes, nodes=nodes)


def _verify_meta_log_append(image: str, state: dict, mode: str, k: int,
                            complete: bool) -> list[str]:
    return _verify_meta_log_image(image, state["expected"],
                                  state["new"], mode, complete)


def _run_meta_log_compact(root: str, state: dict) -> None:
    _fresh_meta_store(root).compact()


def _verify_meta_log_compact(image: str, state: dict, mode: str, k: int,
                             complete: bool) -> list[str]:
    violations = _verify_meta_log_image(image, state["expected"], None,
                                        mode, complete)
    # old journal or new journal, never neither: the shared oracle
    # already proved every durable ref readable; pin that the journal
    # FILE survived every image (a missing journal is an empty store)
    from chunky_bits_tpu.cluster import meta_log as _ml

    if not os.path.isfile(os.path.join(image, _ml.JOURNAL_NAME)):
        violations.append("compaction crash left no journal at all")
    if complete:
        store = _fresh_meta_store(image)
        # a completed compaction is an acknowledged swap (tmp fsync +
        # rename + dir fsync): the reclaim must survive both power-cut
        # extremes...
        if store.dead_bytes() != 0:
            violations.append("completed compaction rolled back "
                              f"(mode={mode}: dead bytes resurfaced)")
        # ...and so must the generation floor record — a counter that
        # ran backwards would hand re-used generations to changes()
        # cursors
        if store.generation() < state["gen"]:
            violations.append(
                f"generation ran backwards across compaction "
                f"({store.generation()} < {state['gen']}, mode={mode})")
        # ...and so must the index projections (scrub pre-scan / GC
        # fast paths): a compaction that dropped them would silently
        # demote every consumer to the fallback read forever
        for name in state["expected"]:
            entry = store.lookup(name)
            hashes, nodes = _proj_of(name)
            if entry is not None and (
                    entry.hashes != tuple(hashes)
                    or entry.nodes != tuple(
                        tuple(p) for p in nodes)):
                violations.append(
                    f"index projection lost across compaction "
                    f"({name!r}, mode={mode})")
                break
    return violations


MUTATIONS: dict[str, Mutation] = {
    m.name: m for m in (
        Mutation("slab_append", _setup_slab, _run_slab_append,
                 _verify_slab_append),
        Mutation("slab_mark_dead", _setup_slab, _run_slab_mark_dead,
                 _verify_slab_mark_dead),
        Mutation("slab_compact", _setup_slab, _run_slab_compact,
                 _verify_slab_compact),
        Mutation("chunk_publish", _setup_publish, _run_publish,
                 _verify_publish),
        Mutation("repair_rewrite", _setup_repair, _run_repair_rewrite,
                 _verify_publish),
        Mutation("metadata_publish", _setup_metadata, _run_metadata,
                 _verify_metadata),
        Mutation("meta_log_append", _setup_meta_log,
                 _run_meta_log_append, _verify_meta_log_append),
        Mutation("meta_log_compact", _setup_meta_log,
                 _run_meta_log_compact, _verify_meta_log_compact),
    )
}


def run_matrix(workdir: str, *, seed: int = 0,
               mutations: Optional[list[str]] = None
               ) -> CrashMatrixResult:
    """Enumerate and verify the full crash matrix for the selected
    mutations under ``workdir``.  Deterministic: same seed ⇒ same op
    streams (shape), same images, same verdicts, same digest."""
    names = sorted(mutations) if mutations is not None \
        else sorted(MUTATIONS)
    unknown = [n for n in names if n not in MUTATIONS]
    if unknown:
        raise ValueError(f"unknown mutation(s) {unknown} "
                         f"(know {sorted(MUTATIONS)})")
    verdicts: list[CrashVerdict] = []
    streams: dict[str, list[FsOp]] = {}
    for name in names:
        mutation = MUTATIONS[name]
        rng = random.Random(seed * 7_919 + len(name))
        base = os.path.join(workdir, name, "base")
        snap = os.path.join(workdir, name, "snap")
        shutil.rmtree(os.path.join(workdir, name), ignore_errors=True)
        os.makedirs(base)
        state = mutation.setup(base, rng)
        shutil.copytree(base, snap, dirs_exist_ok=True)
        ops = record_mutation(base, lambda: mutation.run(base, state))
        if not ops:
            raise AssertionError(
                f"mutation {name} recorded no durability ops — the "
                "seam is not wired through its write path")
        streams[name] = ops
        replayer = OpReplayer(snap)
        image_root = os.path.join(workdir, name, "img")
        for k in range(len(ops) + 1):
            complete = k == len(ops)
            for mode, variant, params in replayer.variants(ops, k):
                shutil.rmtree(image_root, ignore_errors=True)
                replayer.build(ops, k, mode, image_root,
                               torn=params.get("torn"),
                               keep=params.get("keep", frozenset()))
                violations = mutation.verify(image_root, state, mode,
                                             k, complete)
                verdicts.append(CrashVerdict(
                    name, mode, k, variant, not violations,
                    violations))
        shutil.rmtree(os.path.join(workdir, name), ignore_errors=True)
    return CrashMatrixResult(
        verdicts=verdicts,
        ops_by_mutation={n: len(s) for n, s in streams.items()},
        digest=matrix_digest(streams, verdicts))


# ---- cluster-level recovery: crash image + scrub --once -> Valid ----

def run_cluster_recovery(workdir: str, *, seed: int = 0,
                         points: str = "full") -> list[CrashVerdict]:
    """The issue's end-to-end case: a real erasure-coded cluster (five
    ``slab:`` destinations, path metadata) ingests an object while ONE
    destination records; every selected crash image of that
    destination — including the journal-line-without-slab-bytes
    power-cut image — is spliced back under a COLD cluster, and
    ``scrub --once`` (the production daemon + repair planner) must
    converge both objects to Valid with byte-identical reads.

    ``points``: ``"smoke"`` verifies the completed-mutation power-cut
    images only; ``"full"`` adds the start/middle kill images."""
    # the write path's jitter draws ride the process-global RNG; the
    # impl pins it so op streams replay identically run to run —
    # bracket the pin here so the caller's stream is restored whatever
    # happens (scenario.py's bracketing discipline)
    previous_random_state = random.getstate()
    try:
        return _cluster_recovery_impl(workdir, seed=seed, points=points)
    finally:
        random.setstate(previous_random_state)


def _cluster_recovery_impl(workdir: str, *, seed: int,
                           points: str) -> list[CrashVerdict]:
    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.file import FileIntegrity
    from chunky_bits_tpu.utils import aio

    workdir = os.path.abspath(workdir)
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir)
    rng = random.Random(seed + 17)
    random.seed(seed * 2_654_435_761 + 131)

    def cluster_obj(root: str) -> dict:
        return {
            "destinations": [
                {"location": f"slab:{os.path.join(root, f'd{i}')}"}
                for i in range(5)],
            "metadata": {"type": "path", "format": "yaml",
                         "path": os.path.join(root, "meta")},
            "profiles": {"default": {"data": 3, "parity": 2,
                                     "chunk_size": 12}},
        }

    base = os.path.join(workdir, "base")
    os.makedirs(base)
    payloads = {"obj1": rng.randbytes(8 << 10),
                "obj2": rng.randbytes(8 << 10)}

    async def write_one(root: str, name: str) -> None:
        cluster = Cluster.from_obj(cluster_obj(root))
        try:
            await cluster.write_file(
                name, aio.BytesReader(payloads[name]),
                cluster.get_profile())
        finally:
            await cluster.tunables.location_context().aclose()

    asyncio.run(write_one(base, "obj1"))  # durable pre-state
    d0 = os.path.join(base, "d0")
    snap_d0 = os.path.join(workdir, "snap_d0")
    shutil.copytree(d0, snap_d0)
    ops = record_mutation(
        d0, lambda: asyncio.run(write_one(base, "obj2")))
    if not ops:
        raise AssertionError("object ingest recorded no ops on d0")
    # chunk locations in the metadata are absolute paths, so every
    # crash image is spliced back AT ``base`` (a copied tree would
    # leave the refs pointing at the pristine original — a vacuously
    # green verifier); ``final`` preserves the post-ingest state each
    # image restarts from
    final = os.path.join(workdir, "final")
    shutil.copytree(base, final)
    replayer = OpReplayer(snap_d0)
    n = len(ops)
    if points == "smoke":
        selected: list[tuple[int, str]] = [(n, "powercut")]
    else:
        selected = [(0, "kill"), (n // 2, "kill"), (n, "kill"),
                    (n // 2, "powercut"), (n, "powercut"),
                    (n, "powercut-meta")]

    async def scrub_and_verify(root: str) -> list[str]:
        from chunky_bits_tpu.cluster.scrub import ScrubDaemon

        violations: list[str] = []
        cluster = Cluster.from_obj(cluster_obj(root))
        try:
            daemon = ScrubDaemon(cluster, bytes_per_sec=0,
                                 interval_seconds=3600.0, planner=True)
            await daemon.run_once()
            for name, payload in sorted(payloads.items()):
                try:
                    ref = await cluster.get_file_ref(name)
                except Exception as err:  # noqa: BLE001 — a lost ref
                    # IS the verdict for the image under test
                    if name == "obj2":
                        continue  # ingest never acknowledged: clean
                        # not-found is a legal (and detectable) outcome
                    violations.append(f"{name} ref unreadable: {err}")
                    continue
                report = await ref.verify()
                if report.integrity() != FileIntegrity.VALID:
                    violations.append(
                        f"{name} not Valid after scrub --once: "
                        f"{report.integrity()}")
                got = await cluster.file_read_builder(ref).read_all()
                if got != payload:
                    violations.append(f"{name} bytes diverged after "
                                      "recovery")
        finally:
            await cluster.tunables.location_context().aclose()
        return violations

    verdicts: list[CrashVerdict] = []
    for k, mode in selected:
        # the powercut mask that keeps the journal handle but drops
        # the slab-data handle is the documented flush-only window;
        # enumerate every mask at this k and test the worst ones
        variants = [(m, v, p) for m, v, p in replayer.variants(ops, k)
                    if m == mode] or [(mode, "", {})]
        for mode_name, variant, params in variants:
            shutil.rmtree(base)
            shutil.copytree(final, base)
            shutil.rmtree(d0)
            replayer.build(ops, k, mode_name, d0,
                           torn=params.get("torn"),
                           keep=params.get("keep", frozenset()))
            violations = asyncio.run(scrub_and_verify(base))
            verdicts.append(CrashVerdict(
                "cluster_scrub_recovery", mode_name, k, variant,
                not violations, violations))
    return verdicts
