"""The fault-injection node plane: simulated storage nodes behind the
``Location`` surface.

A :class:`SimFabric` is a set of in-process storage nodes addressed as
``sim:<fabric>/<node>/<chunk>`` locations — the same lazy-dispatch
trick as ``slab:`` (``file/location.py`` imports this module only
inside its ``sim:`` branches, so production paths never load it).
Chunk bytes live in per-node dicts; every verb charges a
**distribution-driven virtual latency** (lognormal body + configurable
tail — the shape Dean & Barroso's "The Tail at Scale" hedging exists
for), **byte-accounted virtual bandwidth** (transfer seconds =
bytes / node bandwidth), and the node's **fault state machine**:

    healthy → slow → erroring → partitioned → dead → recovering → healthy

with any state reachable from any other (a scenario script is the
operator; the machine validates only that the *name* is known).  The
semantics per state:

* ``healthy``     — model latency, full service.
* ``slow``        — latency × ``slow_factor`` (config 8's one-slow-node
  generalized; the hedged-read straggler).
* ``erroring``    — latency, then a transient HTTP-status error
  (``error_status``, default 503 — the retry/breaker feeder).
* ``partitioned`` — the request stalls ``partition_stall_s`` of
  virtual time, then times out (an unreachable peer, not a refused
  one).
* ``dead``        — immediate connection-refused error (process gone).
* ``recovering``  — serves with latency × ``recover_factor``; lapses
  to ``healthy`` after ``recover_s`` of virtual time (computed lazily
  from the clock seam — no timer to leak).

Latency samples come from a per-node ``random.Random`` seeded from
``(fabric seed, node id)``, so a scenario replays byte-identically
under the virtual loop: same seed ⇒ same sample sequence ⇒ same trace
(pinned by tests/test_sim.py).

Health integration costs nothing: ``cluster/health.py`` keys non-http
locations by ``os.path.dirname(target)``, which for
``<fabric>/<node>/<chunk>`` is exactly the node — the scoreboard,
breaker and hedge machinery see sim nodes as first-class peers.

:class:`FaultInjector` is the injection core shared with
``tests/http_node.py``'s real-socket fake node (the one-shot
``put_fail_status`` / ``get_delay`` knobs those tests script are model
instances here, not a duplicated if-chain there).
"""

from __future__ import annotations

import math
import random
import threading
from typing import Callable, Optional

from chunky_bits_tpu.errors import HttpStatusError, LocationError
from chunky_bits_tpu.utils import clock as _clock

__all__ = [
    "DEAD",
    "ERRORING",
    "HEALTHY",
    "PARTITIONED",
    "RECOVERING",
    "SLOW",
    "STATES",
    "FaultInjector",
    "LatencyModel",
    "SimFabric",
    "SimNode",
    "get_fabric",
    "resolve",
]

# ---- fault states ----

HEALTHY = "healthy"
SLOW = "slow"
ERRORING = "erroring"
PARTITIONED = "partitioned"
DEAD = "dead"
RECOVERING = "recovering"

STATES = (HEALTHY, SLOW, ERRORING, PARTITIONED, DEAD, RECOVERING)


class LatencyModel:
    """Lognormal latency body with a configurable heavy tail.

    ``sample`` draws ``exp(N(ln(median), sigma))`` seconds and, with
    probability ``tail_p``, multiplies by ``tail_mult`` — the
    occasionally-terrible-response shape real fleets exhibit and the
    hedge machinery is designed against.  Deterministic given the
    caller's seeded ``random.Random``."""

    def __init__(self, median_ms: float = 2.0, sigma: float = 0.45,
                 tail_p: float = 0.01, tail_mult: float = 25.0) -> None:
        if median_ms <= 0:
            raise ValueError(f"median_ms must be > 0, got {median_ms}")
        self.median_s = median_ms / 1000.0
        self.sigma = max(float(sigma), 0.0)
        self.tail_p = min(max(float(tail_p), 0.0), 1.0)
        self.tail_mult = max(float(tail_mult), 1.0)

    def sample(self, rng: random.Random) -> float:
        s = self.median_s * math.exp(rng.gauss(0.0, self.sigma))
        if self.tail_p > 0 and rng.random() < self.tail_p:
            s *= self.tail_mult
        return s


class FaultInjector:
    """Scriptable per-verb fault decisions — the knob surface the old
    ``tests/http_node.py`` if-chains exposed, as one reusable model.

    * ``get_delay``          — every read stalls this long first (the
      straggler knob; 0 = off).
    * ``fail_puts``          — every write answers 507 (broken disk).
    * ``put_fail_status``/``put_fail_remaining`` — the next N writes
      answer with this status, then normal service resumes (the
      transient-retry script).
    * ``torn_put_bytes``/``torn_put_remaining`` — the next N writes
      are ACKED but persist only a prefix of the payload: the silent
      torn write the crash harness (``sim/crash.py``) enumerates at
      syscall scale, scriptable here at fleet scale.  Only the
      content-address gate can catch it afterwards — the disk-fault
      axis of scenario scripting (``disk_corruption_storm``)."""

    def __init__(self, fail_puts: bool = False) -> None:
        self.get_delay = 0.0
        self.fail_puts = fail_puts
        self.put_fail_status = 0
        self.put_fail_remaining = 0
        self.torn_put_bytes = 0
        self.torn_put_remaining = 0

    def get_fault(self) -> float:
        """Seconds a read must stall before being served."""
        return self.get_delay

    def put_fault(self) -> int:
        """HTTP status a write must fail with (0 = serve normally).
        One-shot statuses consume their budget here."""
        if self.put_fail_remaining > 0:
            self.put_fail_remaining -= 1
            return self.put_fail_status or 503
        if self.fail_puts:
            return 507
        return 0

    def torn_fault(self, nbytes: int) -> Optional[int]:
        """Bytes the next write silently keeps (None = write whole).
        One-shot budget, like ``put_fault`` — consumed only when the
        write actually tears (a payload already shorter than the torn
        prefix cannot tear, and must not burn the budget)."""
        if self.torn_put_remaining > 0 and 0 < self.torn_put_bytes \
                < nbytes:
            self.torn_put_remaining -= 1
            return self.torn_put_bytes
        return None


class SimNode:
    """One simulated storage node; all service verbs live here.

    State reads/writes are plain attribute flips on the owning loop's
    thread (scenario scripts and service coroutines share the loop);
    the byte counters are lock-guarded because a metrics scrape may
    read them cross-thread, same as every other stats source."""

    def __init__(self, fabric: "SimFabric", node_id: str, zone: str,
                 latency: LatencyModel, bandwidth_bps: float,
                 seed: int) -> None:
        self.fabric = fabric
        self.node_id = node_id
        self.zone = zone
        self.latency = latency
        self.bandwidth_bps = float(bandwidth_bps)
        self.store: dict[str, bytes] = {}
        self.rng = random.Random(seed)
        self.state = HEALTHY
        self.state_since = _clock.monotonic()
        #: fault-shape knobs (scenario scripts tune per node)
        self.slow_factor = 10.0
        self.recover_factor = 3.0
        self.recover_s = 10.0
        self.partition_stall_s = 5.0
        self.error_status = 503
        #: scripted per-verb injection on top of the state machine
        #: (the tests/http_node.py knob surface)
        self.faults = FaultInjector()
        self._lock = threading.Lock()
        self.ops = 0
        self.bytes_read = 0
        self.bytes_written = 0
        self.errors_injected = 0
        self.torn_writes = 0

    # ---- state machine ----

    def set_state(self, state: str) -> None:
        """Operator/scenario transition; any known state is reachable
        from any other (a crash can interrupt a recovery)."""
        if state not in STATES:
            raise ValueError(f"unknown node state {state!r} "
                             f"(know {STATES})")
        prev = self.state
        self.state = state
        self.state_since = _clock.monotonic()
        self.fabric.trace("node_state", node=self.node_id,
                          zone=self.zone, state=state, prev=prev)

    def effective_state(self) -> str:
        """The state the next request observes — ``recovering`` lapses
        to ``healthy`` after ``recover_s`` without needing a timer."""
        if (self.state == RECOVERING
                and _clock.monotonic() - self.state_since
                >= self.recover_s):
            self.set_state(HEALTHY)
        return self.state

    # ---- service plumbing ----

    def _bump(self, **deltas: int) -> None:
        with self._lock:
            for key, delta in deltas.items():
                setattr(self, key, getattr(self, key) + delta)

    async def _serve(self, verb: str, nbytes: int) -> None:
        """The shared front half of every verb: fault gate, latency,
        virtual bandwidth.  Raises the location-level error a real
        node in this state would produce."""
        self._bump(ops=1)
        state = self.effective_state()
        target = f"{self.fabric.fabric_id}/{self.node_id}"
        if state == DEAD:
            self._bump(errors_injected=1)
            raise LocationError(
                f"sim node {target} refused connection (dead)")
        if state == PARTITIONED:
            await _clock.sleep(self.partition_stall_s)
            self._bump(errors_injected=1)
            raise LocationError(
                f"sim node {target} timed out (partitioned)")
        delay = self.latency.sample(self.rng)
        if state == SLOW:
            delay *= self.slow_factor
        elif state == RECOVERING:
            delay *= self.recover_factor
        if verb == "get":
            delay += self.faults.get_fault()
        if self.bandwidth_bps > 0 and nbytes > 0:
            delay += nbytes / self.bandwidth_bps
        await _clock.sleep(delay)
        if state == ERRORING:
            self._bump(errors_injected=1)
            raise HttpStatusError(self.error_status, target)
        if verb == "put":
            status = self.faults.put_fault()
            if status:
                self._bump(errors_injected=1)
                raise HttpStatusError(status, target)

    # ---- the verbs (file/location.py's sim: branches call these) ----

    async def read(self, name: str, start: int = 0,
                   length: Optional[int] = None) -> bytes:
        data = self.store.get(name)
        nbytes = 0 if data is None else \
            len(data[start: None if length is None else start + length])
        await self._serve("get", nbytes)
        if data is None:
            raise LocationError(
                f"no chunk {name!r} on sim node {self.node_id}")
        if start < 0 or (length is not None and length < 0):
            raise LocationError(f"negative range on sim chunk {name!r}")
        out = data[start: None if length is None else start + length]
        self._bump(bytes_read=len(out))
        return out

    async def write(self, name: str, data: bytes) -> None:
        await self._serve("put", len(data))
        torn = self.faults.torn_fault(len(data))
        if torn is not None:
            # silent torn write: the node ACKS the put but persists
            # only a prefix — detectable later solely by the
            # content-address gate (scrub's next pass re-reads,
            # mismatches, and repairs again)
            self.store[name] = bytes(data[:torn])
            self._bump(bytes_written=torn, torn_writes=1)
            self.fabric.trace("torn_write", node=self.node_id,
                              chunk=name, kept=torn, total=len(data))
            return
        self.store[name] = bytes(data)
        self._bump(bytes_written=len(data))

    async def delete(self, name: str) -> None:
        await self._serve("delete", 0)
        self.store.pop(name, None)

    async def exists(self, name: str) -> bool:
        await self._serve("head", 0)
        return name in self.store

    async def length(self, name: str) -> int:
        await self._serve("head", 0)
        data = self.store.get(name)
        if data is None:
            raise LocationError(
                f"no chunk {name!r} on sim node {self.node_id}")
        return len(data)

    # ---- direct (fault-free) access for scenario damage scripts ----

    def corrupt(self, name: str, offset: int, xor: int = 0x01) -> bool:
        """Flip one byte of a stored chunk in place (no latency, no
        fault gate — this is the scenario injecting damage, not a
        client doing I/O).  False when the chunk is absent."""
        data = self.store.get(name)
        if data is None or not data:
            return False
        offset %= len(data)
        raw = bytearray(data)
        raw[offset] ^= xor
        self.store[name] = bytes(raw)
        return True

    def drop(self, name: str) -> bool:
        """Remove a stored chunk outright (disk sector loss)."""
        return self.store.pop(name, None) is not None

    def stats(self) -> dict:
        with self._lock:
            return {
                "node": self.node_id,
                "zone": self.zone,
                "state": self.state,
                "chunks": len(self.store),
                "ops": self.ops,
                "bytes_read": self.bytes_read,
                "bytes_written": self.bytes_written,
                "errors_injected": self.errors_injected,
                "torn_writes": self.torn_writes,
            }


#: process-wide fabric registry — the ``slab.get_store`` analogue: the
#: registry is how a parsed ``sim:`` Location string finds its live
#: in-process node.  Re-registering an id replaces the old fabric (a
#: scenario re-run with the same id starts from a fresh node set).
_FABRICS: dict[str, "SimFabric"] = {}


def get_fabric(fabric_id: str) -> "SimFabric":
    fabric = _FABRICS.get(fabric_id)
    if fabric is None:
        raise LocationError(
            f"no live sim fabric {fabric_id!r} — sim: locations only "
            "resolve inside a simulator run")
    return fabric


def resolve(target: str) -> tuple[SimNode, str]:
    """``(node, chunk name)`` for a sim location target
    ``<fabric>/<node>/<chunk>`` (the string form the metadata plane
    round-trips)."""
    parts = target.split("/", 2)
    if len(parts) != 3 or not all(parts):
        raise LocationError(
            f"sim location {target!r} does not name "
            "<fabric>/<node>/<chunk>")
    fabric_id, node_id, name = parts
    fabric = get_fabric(fabric_id)
    node = fabric.nodes.get(node_id)
    if node is None:
        raise LocationError(
            f"no node {node_id!r} in sim fabric {fabric_id!r}")
    return node, name


class SimFabric:
    """A registered set of simulated nodes with zone topology.

    ``trace_hook`` (set by the scenario engine) receives every fabric
    event as ``(virtual_time, event, fields)`` — the seed-reproducible
    event trace.  Without a hook events are dropped (bare fabrics in
    unit tests)."""

    def __init__(self, fabric_id: str, n_nodes: int,
                 zones: tuple[str, ...] = ("az0", "az1", "az2"),
                 seed: int = 0,
                 latency: Optional[LatencyModel] = None,
                 bandwidth_bps: float = 200e6) -> None:
        if n_nodes <= 0:
            raise ValueError(f"n_nodes must be > 0, got {n_nodes}")
        if not zones:
            raise ValueError("need at least one zone")
        self.fabric_id = fabric_id
        self.seed = seed
        self.zones = tuple(zones)
        self.trace_hook: Optional[Callable[[float, str, dict], None]] \
            = None
        latency = latency or LatencyModel()
        self.nodes: dict[str, SimNode] = {}
        for i in range(n_nodes):
            node_id = f"n{i:04d}"
            zone = self.zones[i % len(self.zones)]
            # per-node rng seeded from (fabric seed, index): stable
            # across runs, independent across nodes
            self.nodes[node_id] = SimNode(
                self, node_id, zone, latency, bandwidth_bps,
                seed=(seed * 1_000_003 + i))
        _FABRICS[fabric_id] = self

    # ---- topology ----

    def nodes_in_zone(self, zone: str) -> list[SimNode]:
        return [n for n in self.nodes.values() if n.zone == zone]

    def set_zone_state(self, zone: str, state: str) -> None:
        """Zone-wide transition (the AZ-outage primitive)."""
        hit = self.nodes_in_zone(zone)
        if not hit:
            raise ValueError(f"no nodes in zone {zone!r}")
        for node in hit:
            node.set_state(state)

    def destination_objs(self) -> list[dict]:
        """Cluster-config destination entries for every node — feed
        straight into ``Cluster.from_obj``'s ``destinations`` (zone
        tags ride along, so ``zone_rules`` placement caps work)."""
        return [
            {"location": f"sim:{self.fabric_id}/{node_id}",
             "zones": [node.zone]}
            for node_id, node in self.nodes.items()
        ]

    # ---- tracing / teardown ----

    def trace(self, event: str, **fields: object) -> None:
        hook = self.trace_hook
        if hook is not None:
            hook(_clock.monotonic(), event, fields)

    def stats(self) -> dict:
        by_state: dict[str, int] = {}
        for node in self.nodes.values():
            by_state[node.state] = by_state.get(node.state, 0) + 1
        return {
            "fabric": self.fabric_id,
            "nodes": len(self.nodes),
            "zones": list(self.zones),
            "by_state": dict(sorted(by_state.items())),
            "chunks": sum(len(n.store) for n in self.nodes.values()),
            "errors_injected": sum(n.errors_injected
                                   for n in self.nodes.values()),
        }

    def close(self) -> None:
        """Unregister; parsed ``sim:`` locations stop resolving (the
        metadata outliving a run must fail loudly, not serve stale
        node dicts)."""
        if _FABRICS.get(self.fabric_id) is self:
            del _FABRICS[self.fabric_id]
