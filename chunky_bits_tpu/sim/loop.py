"""Virtual-time asyncio event loop: the simulator's timebase.

A ``SelectorEventLoop`` subclass whose ``time()`` is a virtual float
that only advances when the loop would otherwise *wait*: the wrapped
selector first polls the real file descriptors with timeout 0 (the
self-pipe that ``call_soon_threadsafe`` writes, any real sockets a test
mixes in), and only when nothing is ready, no host-thread work is in
flight, and a timer is scheduled does it jump virtual time straight to
that timer.  A 60-minute scrub interval therefore costs one callback
dispatch of wall time, while every duration, cooldown, EWMA decay and
budget accrual measured through the clock seam (``cluster/clock.py``)
agrees on the same virtual timebase.

**Real work still completes.**  Filesystem hops (``asyncio.to_thread``,
``aio.open_in_thread``) run on real threads; the loop tracks them by
overriding ``run_in_executor`` and refuses to advance virtual time
while any are outstanding — it blocks in a *bounded* real select slice
(``_REAL_WAIT_SLICE``) until the completion lands on the self-pipe.
Thread work thus takes **zero virtual time**, which is exactly the
semantics the scenarios need: the only virtual durations are the ones
the fault models inject.  (Host-pipeline jobs above its 128 KiB inline
bound complete the same way but are not *tracked*; scenario payloads
stay under the bound so virtual time can never jump over an in-flight
hash — see sim/scenario.py.)

**Determinism.**  Given a seeded scenario, callback order is the loop's
own FIFO ready queue and timer heap — no wall-clock jitter enters the
schedule, because real-time effects (thread completions) are absorbed
at zero virtual width before any timer may fire.  tests/test_sim.py
pins byte-identical event traces across runs of the same seed.

**Sanitizer.**  ``run()`` instruments the loop with the active runtime
sanitizer (watchdog heartbeat, task registry) when one is installed —
reached via ``sys.modules`` like every hot-path hook, so the off path
imports nothing — and tears down asyncio.run-style: cancel + await
every remaining task, shutdown async generators and the default
executor, close the loop.  The SANITIZE=1 tier-1 leg runs the sim
tests with 0 leaked tasks.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import selectors
import sys
import threading
from typing import Any, Callable, Optional

from chunky_bits_tpu.utils import clock as clock_mod

__all__ = ["VirtualTimeLoop", "run"]

#: bound on one real select slice while host threads are in flight (or
#: while the loop waits on real FDs with no timer armed): completions
#: wake the loop immediately through the self-pipe; the slice only caps
#: how long a *stuck* thread can keep the simulator unresponsive to a
#: stop request
_REAL_WAIT_SLICE = 0.2


class _VirtualSelector:
    """Selector facade that converts "would block" into virtual-time
    jumps.  Wraps the loop's real selector; every method except
    ``select`` passes straight through."""

    def __init__(self, base: selectors.BaseSelector,
                 loop: "VirtualTimeLoop") -> None:
        self._base = base
        self._loop = loop

    def select(self, timeout: Optional[float] = None) -> list:
        # Real readiness always wins: the self-pipe (threadsafe wakeups,
        # thread completions, watchdog heartbeats) and any real sockets
        # are serviced before time may move.
        events = self._base.select(0)
        if events or timeout == 0:
            return events
        if self._loop._external_pending():
            # host-thread work in flight: wait for it in REAL time —
            # virtual time must not jump over an unfinished disk read.
            # The completion's call_soon_threadsafe write wakes the
            # select immediately; the slice bounds a stuck thread.
            wait = _REAL_WAIT_SLICE if timeout is None \
                else min(timeout, _REAL_WAIT_SLICE)
            return self._base.select(wait)
        if timeout is None:
            # No timers, nothing ready, no threads: the loop is waiting
            # on real FDs (a test mixing real sockets in) or plainly
            # stuck — either way only real time can resolve it.  Wait
            # in bounded slices so the loop stays interruptible
            # (degrade, never hang).
            return self._base.select(_REAL_WAIT_SLICE)
        # Quiescent with a timer armed: this is the compression step —
        # jump straight to the timer.
        self._loop._advance(timeout)
        return []

    def __getattr__(self, name: str) -> Any:
        return getattr(self._base, name)


class VirtualTimeLoop(asyncio.SelectorEventLoop):
    """The virtual-time loop; see the module docstring.  Construct via
    :func:`run` (which also installs the clock seam's VirtualClock) —
    a bare instance still works as a plain loop whose ``time()``
    happens to be virtual."""

    def __init__(self) -> None:
        super().__init__()
        self._virtual_now = 0.0
        # external (host-thread) work accounting: incremented on the
        # loop thread at submit; decremented by the wrapped future's
        # completion callback, which may run on a worker thread — hence
        # the lock (a bare int += is GIL-atomic today, but the contract
        # should not hang off that)
        self._ext_lock = threading.Lock()
        self._ext_jobs = 0
        # ONE worker, FIFO: thread hops complete in submission order,
        # so their zero-virtual-width completions interleave the ready
        # queue identically on every run of the same seed (the
        # determinism the trace pin relies on).  Throughput is
        # irrelevant here — thread work takes zero virtual time either
        # way.  Shut down by run()'s teardown, never by interpreter
        # exit with work parked (the jobs are bounded local file I/O).
        self._serial_executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cb-sim-io")
        self._selector = _VirtualSelector(self._selector, self)

    # ---- virtual time ----

    def time(self) -> float:
        return self._virtual_now

    def _advance(self, seconds: float) -> None:
        self._virtual_now += seconds

    # ---- external (threaded) work tracking ----

    def _external_pending(self) -> bool:
        with self._ext_lock:
            return self._ext_jobs > 0

    def _external_done(self, _fut: object) -> None:
        with self._ext_lock:
            self._ext_jobs -= 1

    def run_in_executor(self, executor: Any, func: Callable, *args: Any):
        if executor is None:
            executor = self._serial_executor
        fut = super().run_in_executor(executor, func, *args)
        with self._ext_lock:
            self._ext_jobs += 1
        # the wrapped asyncio future completes via the loop (the
        # self-pipe wakeup is the signal the selector blocks for), so
        # the decrement can never land "early" — virtual time stays
        # frozen until the result is deliverable
        fut.add_done_callback(self._external_done)
        return fut


def _sanitizer():
    """The active runtime sanitizer, without importing it: the module
    is only present when ``CHUNKY_BITS_TPU_SANITIZE`` loaded it (the
    same ``sys.modules`` door parallel/host_pipeline.py uses)."""
    mod = sys.modules.get("chunky_bits_tpu.analysis.sanitizer")
    return mod.active() if mod is not None else None


def _cancel_all_tasks(loop: asyncio.AbstractEventLoop) -> None:
    """asyncio.runners' teardown shape: cancel every remaining task and
    run the loop until they finish, so nothing leaks past the sim run
    (the SANITIZE=1 contract)."""
    to_cancel = asyncio.all_tasks(loop)
    if not to_cancel:
        return
    for task in to_cancel:
        task.cancel()
    loop.run_until_complete(
        asyncio.gather(*to_cancel, return_exceptions=True))
    for task in to_cancel:
        if task.cancelled():
            continue
        if task.exception() is not None:
            loop.call_exception_handler({
                "message": "unhandled exception during sim.run() "
                           "shutdown",
                "exception": task.exception(),
                "task": task,
            })


def run(main, *, debug: Optional[bool] = None):
    """``asyncio.run`` for simulated time: execute ``main`` on a fresh
    :class:`VirtualTimeLoop` with the clock seam pointing at it.

    Brackets the whole run: installs a ``VirtualClock`` bound to the
    loop (so every ``cluster/clock.py`` read — EWMA decay, breaker
    cooldowns, token buckets, hedge delays — ticks in virtual time),
    restores the previous clock on the way out, and tears the loop down
    asyncio.run-style.  Everything time-sensitive the coroutine builds
    (clusters, scoreboards, scrub daemons) must be constructed *inside*
    it — a TokenBucket built on the real clock would see a huge
    backwards jump when virtual time starts at 0."""
    if asyncio.events._get_running_loop() is not None:
        raise RuntimeError(
            "sim.run() cannot be called from a running event loop")
    loop = VirtualTimeLoop()
    san = _sanitizer()
    if san is not None:
        # loops built by the sanitizer's policy are auto-instrumented;
        # this one is constructed directly, so opt in explicitly
        san.instrument_loop(loop)
    previous_clock = clock_mod.install(clock_mod.VirtualClock(loop))
    try:
        asyncio.set_event_loop(loop)
        if debug is not None:
            loop.set_debug(debug)
        return loop.run_until_complete(main)
    finally:
        # the VirtualClock stays installed through teardown: cancelled
        # tasks run their cleanup (error paths computing
        # `monotonic() - start` latency samples) on the still-virtual
        # loop, and restoring the real clock first would mix timebases
        # in exactly the way CB108 forbids
        try:
            _cancel_all_tasks(loop)
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.run_until_complete(loop.shutdown_default_executor())
        finally:
            clock_mod.install(previous_clock)
            loop._serial_executor.shutdown(wait=True)
            asyncio.set_event_loop(None)
            loop.close()
