"""The scenario engine: scripted fleet-scale fault timelines with
convergence invariants and a seed-reproducible event trace.

A scenario is a coroutine driving a :class:`ScenarioEnv` — a simulated
cluster (``sim/fabric.py`` nodes behind real ``Cluster`` machinery)
running on the virtual-time loop (``sim/loop.py``), with a fresh
metrics registry as the observer.  The engine provides the shared
plumbing every scenario needs:

* a **generated namespace** (seeded payloads, real erasure-coded
  writes through the production writer/placement path, zone-capped by
  ``zone_rules`` so any single-AZ loss stays within parity);
* a **background client** (sequential seeded reads asserting byte
  identity, failures timestamped against the scripted fault windows);
* the **scrub/repair plane** (the production ``ScrubDaemon`` +
  ``RepairPlanner``, byte-metered in virtual time);
* **invariant verdicts** — namespace returns to Valid, no
  client-visible error outside a fault window, hedge amplification
  within the token-bucket budget, repair bytes within the config-11/13
  structural bounds (copy ≤ 1x, decode = d x, msr = 2x — exact
  per-plan accounting, never estimates);
* **SLO detection verdicts** — every scenario runs the production SLO
  engine (obs/slo.py) over its fresh registry, ticked in virtual time;
  the scenario's ``slo`` spec names which alerts MUST fire (within a
  bounded virtual-time detection latency of the scripted fault, and
  resolve after convergence) and the engine must stay silent otherwise
  (zero firing onsets outside fault windows + grace — deterministic
  precision AND recall for the whole alerting stack, something a real
  cluster can never prove).  Alert transitions are trace events, so
  detection latency is part of the byte-identical determinism pin;
* the **event trace** — every fabric state transition, scripted
  action, client error and verdict as one canonical JSON line with its
  virtual timestamp.  Same seed ⇒ byte-identical trace and equal
  metrics snapshot (tests/test_sim.py pins it; the virtual loop's
  serialized thread plane and the fabric's per-node seeded RNGs are
  what make it true).

``SCENARIOS`` is the library bench ``--config 14`` iterates: AZ outage
mid-scrub, rolling restart (plain and during pm-msr repair),
thundering-herd reads, correlated in-zone disk failures, flapping
node, slow-leak corruption.
"""

from __future__ import annotations

import asyncio
import random
import shutil
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

from chunky_bits_tpu.obs import metrics as obs_metrics
from chunky_bits_tpu.sim import fabric as fabric_mod
from chunky_bits_tpu.sim import loop as sim_loop
from chunky_bits_tpu.utils import clock as _clock

__all__ = [
    "SCENARIOS",
    "ScenarioEnv",
    "ScenarioResult",
    "run_scenario",
]


def _json_line(obj: dict) -> str:
    import json

    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class EventTrace:
    """Ordered (virtual time, event, fields) records; canonical
    serialization is one sorted-key JSON line per event."""

    def __init__(self) -> None:
        self.events: list[tuple[float, str, dict]] = []

    def record(self, t: float, event: str, fields: dict) -> None:
        self.events.append((t, event, dict(fields)))

    def to_bytes(self) -> bytes:
        lines = [
            _json_line({"t": round(t, 6), "event": event, **fields})
            for t, event, fields in self.events
        ]
        return ("\n".join(lines) + "\n").encode("utf-8")


@dataclass
class ScenarioResult:
    """One scenario run's outcome: the bench --config 14 row and the
    determinism test's comparison unit."""

    name: str
    seed: int
    nodes: int
    virtual_seconds: float
    wall_seconds: float
    trace: bytes
    metrics: dict
    verdicts: dict[str, bool]
    details: dict = field(default_factory=dict)

    def ok(self) -> bool:
        return all(self.verdicts.values()) and bool(self.verdicts)

    def compression(self) -> float:
        """Virtual seconds lived per wall second spent — the headline
        the simulator exists for."""
        if self.wall_seconds <= 0:
            return 0.0
        return self.virtual_seconds / self.wall_seconds

    def to_obj(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "nodes": self.nodes,
            "virtual_s": round(self.virtual_seconds, 3),
            "wall_s": round(self.wall_seconds, 3),
            "compression_x": round(self.compression(), 1),
            "ok": self.ok(),
            "verdicts": dict(sorted(self.verdicts.items())),
            "trace_events": self.trace.count(b"\n"),
            **self.details,
        }


class ScenarioEnv:
    """Shared scenario plumbing; see the module docstring.  Construct
    and drive only inside ``sim.run`` — every time-sensitive object it
    builds must be born under the virtual clock."""

    def __init__(self, name: str, workdir: str, *,
                 nodes: int = 100, seed: int = 0,
                 zones: tuple[str, ...] = ("az0", "az1", "az2"),
                 data: int = 3, parity: int = 2, chunk_log2: int = 12,
                 code: str = "rs",
                 objects: int = 24, object_bytes: int = 18_000,
                 hedge_ms: float = 0.0,
                 scrub_bytes_per_sec: float = 0.0,
                 scrub_interval_s: float = 60.0,
                 read_retries: int = 1) -> None:
        import os

        from chunky_bits_tpu.cluster import Cluster

        self.name = name
        self.seed = seed
        self.trace = EventTrace()
        # the global-`random` consumers on the read/write paths (worker
        # pool draws, retry jitter) must replay identically run-to-run
        random.seed(seed * 2_654_435_761 + 97)
        self.rand = random.Random(seed + 1)
        self.fabric = fabric_mod.SimFabric(
            f"sc-{name}", nodes, zones=zones, seed=seed)
        self.fabric.trace_hook = self.trace.record
        self.d, self.p = data, parity
        self.chunk_bytes = 1 << chunk_log2
        meta = os.path.join(workdir, "meta")
        os.makedirs(meta, exist_ok=True)
        # zone cap = parity: any single-AZ loss leaves >= d chunks of
        # every part reachable, so reads survive the outage by
        # reconstruction — the placement rule a real deployment runs
        profile = {
            "data": data, "parity": parity, "chunk_size": chunk_log2,
            "code": code,
            "rules": {z: {"maximum": parity, "ideal": 1}
                      for z in zones},
        }
        self.cluster = Cluster.from_obj({
            "destinations": self.fabric.destination_objs(),
            "metadata": {"type": "path", "format": "yaml", "path": meta},
            "profiles": {"default": profile},
            "tunables": {
                **({"hedge_ms": hedge_ms} if hedge_ms > 0 else {}),
                "read_retries": read_retries,
                # always the process-shared host pipeline (YAML wins
                # over the CI matrix's HOST_THREADS env): a
                # cluster-pinned pipeline would register its
                # wall-clock busy/idle counters with THIS run's fresh
                # registry and break snapshot equality between runs
                "host_threads": 0,
            },
        })
        self.objects = objects
        self.object_bytes = object_bytes
        self.contents: dict[str, bytes] = {}
        self.scrub_interval_s = scrub_interval_s
        self.scrub_rate = scrub_bytes_per_sec
        self._daemon = None
        self._client_task: Optional[asyncio.Task] = None
        self._client_errors: list[tuple[float, str, str]] = []
        self.client_reads = 0
        self._fault_windows: list[list[float]] = []
        self._fault_begins: list[float] = []  # raw (non-backdated)
        self.verdicts: dict[str, bool] = {}
        # SLO engine plumbing (start_slo)
        self.slo_engine = None
        self.slo_spec: dict = {}
        self._slo_task: Optional[asyncio.Task] = None
        self._slo_tick_s = 15.0
        #: every alert state transition as (virtual t, rule, old, new)
        #: — the detection-verdict input, also traced
        self.alert_transitions: list[tuple[float, str, str, str]] = []

    # ---- tracing / verdicts ----

    def now(self) -> float:
        return _clock.monotonic()

    def event(self, event: str, **fields: object) -> None:
        self.trace.record(self.now(), event, fields)

    def verdict(self, name: str, ok: bool, **fields: object) -> None:
        self.verdicts[name] = bool(ok)
        self.event("verdict", verdict=name, ok=bool(ok), **fields)

    async def sleep(self, seconds: float) -> None:
        await _clock.sleep(seconds)

    # ---- fault windows (the reads-clean invariant's exclusions) ----

    def fault_begin(self, backdate_s: float = 30.0) -> None:
        """Open a fault window.  The begin edge is backdated by
        ``backdate_s``: a client read already in flight when the fault
        lands is timestamped at ITS start, and an error it takes from
        the freshly-injected fault belongs to the window, not to the
        healthy period before it (the end edge gets the symmetric
        treatment via ``fault_end``'s grace).  The RAW begin time is
        kept separately: it is the zero point SLO detection latency is
        measured from."""
        self._fault_begins.append(self.now())
        self._fault_windows.append(
            [self.now() - backdate_s, float("inf")])

    def fault_end(self, grace_s: float = 120.0) -> None:
        """Close the most recent open window; clients get ``grace_s``
        beyond it (in-flight requests finish against the fault)."""
        for window in reversed(self._fault_windows):
            if window[1] == float("inf"):
                window[1] = self.now() + grace_s
                return
        raise RuntimeError("fault_end without an open fault window")

    def _in_fault_window(self, t: float) -> bool:
        return any(lo <= t <= hi for lo, hi in self._fault_windows)

    # ---- namespace ----

    async def write_namespace(self) -> None:
        payload_rng = random.Random(self.seed + 2)
        profile = self.cluster.get_profile()
        from chunky_bits_tpu.utils import aio

        for i in range(self.objects):
            name = f"obj{i:04d}"
            payload = payload_rng.randbytes(self.object_bytes)
            await self.cluster.write_file(
                name, aio.BytesReader(payload), profile)
            self.contents[name] = payload
        self.event("namespace_written", objects=self.objects,
                   bytes=self.objects * self.object_bytes)

    async def read_object(self, name: str) -> bool:
        """One client read with byte-identity check; failures are
        timestamped for the reads-clean verdict."""
        t0 = self.now()
        self.client_reads += 1
        try:
            ref = await self.cluster.get_file_ref(name)
            got = await self.cluster.file_read_builder(ref).read_all()
        # lint: broad-except-ok the client records ANY failure shape as
        # a timestamped trace event for the reads-clean verdict — the
        # scenario's assertions decide whether it was allowed
        except Exception as err:
            self._client_errors.append((t0, name, str(err)))
            self.event("client_error", object=name,
                       error=type(err).__name__)
            return False
        if got != self.contents[name]:
            self._client_errors.append((t0, name, "byte mismatch"))
            self.event("client_error", object=name,
                       error="byte-mismatch")
            return False
        return True

    def start_client(self, period_s: float = 5.0) -> None:
        """Sequential background reads, one every ``period_s`` virtual
        seconds, round-robin over the namespace with a seeded shuffle."""
        order_rng = random.Random(self.seed + 3)

        async def client() -> None:
            names = sorted(self.contents)
            while True:
                name = names[order_rng.randrange(len(names))]
                await self.read_object(name)
                await self.sleep(period_s)

        self._client_task = asyncio.ensure_future(client())

    async def stop_client(self) -> None:
        task, self._client_task = self._client_task, None
        if task is None:
            return
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    # ---- SLO engine (the detection-quality harness) ----

    def start_slo(self, spec: Optional[dict] = None) -> None:
        """Run the production SLO engine (obs/slo.py) over this
        scenario's fresh registry, ticked every ``tick_s`` VIRTUAL
        seconds.  ``spec``:

        * ``expected`` — ``{rule: {"within_s": N, "resolve": bool}}``:
          alerts that MUST fire within N virtual seconds of the first
          raw ``fault_begin`` (and, when ``resolve`` is true, be
          resolved again by scenario end);
        * ``objectives`` — SloObjectives overrides (a scenario is an
          operator tuning windows to its fleet's shape);
        * ``tick_s`` — evaluation cadence (default 15 s);
        * ``grace_s`` — how far past a fault window's close an
          expected rule's firing onset may lag (windowed detection
          trails the fault; default ``slow_s + clear_s``).

        Every transition lands in the event trace, so detection
        latency is part of the byte-identical determinism pin."""
        from chunky_bits_tpu.obs import slo as obs_slo

        self.slo_spec = dict(spec or {})
        objectives = obs_slo.SloObjectives.from_obj(
            self.slo_spec.get("objectives") or None)
        self._slo_tick_s = float(self.slo_spec.get("tick_s", 15.0))

        def on_transition(rule: str, old: str, new: str, t: float,
                          value) -> None:
            self.alert_transitions.append((t, rule, old, new))
            self.trace.record(t, "alert", {
                "rule": rule, "from": old, "to": new,
                "value": None if value is None else round(value, 6)})

        self.slo_engine = obs_slo.SloEngine(
            objectives=objectives,
            registry=obs_metrics.get_registry(),
            on_transition=on_transition)

        async def ticker() -> None:
            while True:
                self.slo_engine.observe()
                await self.sleep(self._slo_tick_s)

        self._slo_task = asyncio.ensure_future(ticker())
        self.event("slo_started", tick_s=self._slo_tick_s,
                   expected=sorted(self.slo_spec.get("expected", {})))

    async def stop_slo(self) -> None:
        task, self._slo_task = self._slo_task, None
        if task is None:
            return
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    async def settle_slo(self) -> None:
        """Post-driver settle: keep ticking until every expected
        ``resolve: true`` alert has resolved (bounded — resolution is
        itself under test, so a stuck alert times out into a failed
        verdict rather than a hung scenario)."""
        if self.slo_engine is None:
            return
        expected = self.slo_spec.get("expected", {})
        want_resolved = [rule for rule, cfg in expected.items()
                         if cfg.get("resolve", True)]
        obj = self.slo_engine.objectives
        deadline = self.now() + obj.slow_s + obj.clear_s \
            + 10.0 * self._slo_tick_s
        while self.now() < deadline:
            firing = set(self.slo_engine.firing())
            if not any(rule in firing for rule in want_resolved):
                break
            await self.sleep(self._slo_tick_s)

    def check_slo(self) -> None:
        """The per-rule detection verdicts (every scenario reports
        them — run_scenario calls this after the driver and settle):

        * ``slo_detected_<rule>`` for each expected rule: it fired,
          its first firing onset lies within ``within_s`` of the first
          raw ``fault_begin``, and (when ``resolve`` is true) it is
          resolved by scenario end;
        * ``slo_no_false_positives``: ZERO firing onsets outside the
          scripted fault windows + grace — the precision half of
          detection quality.  A non-expected rule firing INSIDE a
          declared window is a co-detection, not noise (an AZ outage
          legitimately pins the hedge budget too when hedging is
          armed); scenarios with no fault window at all are pure
          silence checks, where any firing is a false positive."""
        if self.slo_engine is None:
            return
        from chunky_bits_tpu.obs import slo as obs_slo

        expected: dict = self.slo_spec.get("expected", {})
        obj = self.slo_engine.objectives
        grace_s = float(self.slo_spec.get(
            "grace_s", obj.slow_s + obj.clear_s))
        fault_t0 = (self._fault_begins[0]
                    if self._fault_begins else None)
        onsets: dict[str, list[float]] = {}
        for t, rule, _old, new in self.alert_transitions:
            if new == obs_slo.FIRING:
                onsets.setdefault(rule, []).append(t)
        final = {a.rule: a.state for a in self.slo_engine.alerts()}
        detect_latency: dict[str, float] = {}
        for rule, cfg in sorted(expected.items()):
            fired = onsets.get(rule, [])
            within = float(cfg.get("within_s", 600.0))
            t0 = fault_t0 if fault_t0 is not None else 0.0
            in_time = bool(fired) and t0 <= fired[0] <= t0 + within
            if fired:
                detect_latency[rule] = round(fired[0] - t0, 3)
            resolved_ok = True
            if cfg.get("resolve", True):
                resolved_ok = final.get(rule) == obs_slo.INACTIVE
            self.verdict(
                f"slo_detected_{rule}", in_time and resolved_ok,
                fired_at=(round(fired[0], 3) if fired else None),
                fault_t0=(round(t0, 3)),
                within_s=within,
                latency_s=detect_latency.get(rule),
                resolved=final.get(rule) == obs_slo.INACTIVE,
                resolve_required=cfg.get("resolve", True))
        false_positives = []
        for rule, times in sorted(onsets.items()):
            for t in times:
                in_window = any(lo <= t <= hi + grace_s
                                for lo, hi in self._fault_windows)
                if not in_window:
                    false_positives.append((rule, round(t, 3)))
        self.verdict("slo_no_false_positives", not false_positives,
                     false_positives=false_positives,
                     evaluations=self.slo_engine.stats().evaluations)
        self._slo_report = {
            "detect_latency_s": detect_latency,
            "false_positives": len(false_positives),
            "transitions": len(self.alert_transitions),
            "expected": sorted(expected),
        }

    def slo_report(self) -> dict:
        """The config-15 row fields (empty when no engine ran)."""
        return dict(getattr(self, "_slo_report", {}) or {})

    # ---- scrub/repair plane ----

    def start_scrub(self, replace_after_s: float = 900.0) -> None:
        from chunky_bits_tpu.cluster.scrub import ScrubDaemon

        self._daemon = ScrubDaemon(
            self.cluster, bytes_per_sec=self.scrub_rate,
            interval_seconds=self.scrub_interval_s, planner=True,
            replace_after_s=replace_after_s)
        self._daemon.start()
        self.event("scrub_started",
                   interval_s=self.scrub_interval_s,
                   rate=self.scrub_rate,
                   replace_after_s=replace_after_s)

    async def stop_scrub(self) -> None:
        if self._daemon is not None:
            await self._daemon.stop()
            self.event("scrub_stopped",
                       passes=self._daemon.stats().passes)

    def scrub_stats(self):
        if self._daemon is None:
            raise RuntimeError("scrub daemon never started")
        return self._daemon.stats()

    # ---- damage scripting (direct fabric access, no client I/O) ----

    async def _locations_of(self, name: str) -> list[tuple[int, int, str]]:
        """(part index, chunk index, sim target) for every replica."""
        ref = await self.cluster.get_file_ref(name)
        out = []
        for pi, part in enumerate(ref.parts):
            for ci, chunk in enumerate(part.data + part.parity):
                for location in chunk.locations:
                    if location.is_sim():
                        out.append((pi, ci, location.target))
        return out

    async def drop_replicas(self, count: int, *,
                            avoid_zones: tuple[str, ...] = (),
                            per_part_limit: int = 1) -> int:
        """Drop ``count`` chunk replicas (sector loss: bytes vanish,
        node stays up) from nodes outside ``avoid_zones``, keeping
        every part's TOTAL damage — drops plus whatever already sits in
        the avoided (partitioned/dead) zones — within parity, so parts
        stay readable and in-place-repairable.  Never more than
        ``per_part_limit`` drops per part.  Seeded choice —
        deterministic.  Returns how many dropped."""
        dropped = 0
        hit: dict[tuple[str, int], int] = {}
        names = sorted(self.contents)
        self.rand.shuffle(names)
        for name in names:
            if dropped >= count:
                break
            locs = await self._locations_of(name)
            unreachable: dict[int, int] = {}
            for pi, _ci, target in locs:
                node, _ = fabric_mod.resolve(target)
                if node.zone in avoid_zones:
                    unreachable[pi] = unreachable.get(pi, 0) + 1
            for pi, ci, target in locs:
                if dropped >= count:
                    break
                node, chunk_name = fabric_mod.resolve(target)
                if node.zone in avoid_zones:
                    continue
                key = (name, pi)
                hits = hit.get(key, 0)
                if hits >= per_part_limit:
                    continue
                if unreachable.get(pi, 0) + hits + 1 > self.p:
                    continue  # would push the part past parity
                if node.drop(chunk_name):
                    hit[key] = hits + 1
                    dropped += 1
                    self.event("replica_dropped", object=name,
                               part=pi, chunk=ci, node=node.node_id)
        return dropped

    async def corrupt_replica(self, name: str, part: int = 0,
                              chunk: int = 0) -> bool:
        """Flip one byte of one replica of ``name`` (latent sector
        rot); offset seeded — deterministic."""
        for pi, ci, target in await self._locations_of(name):
            if pi == part and ci == chunk:
                node, chunk_name = fabric_mod.resolve(target)
                if node.corrupt(chunk_name,
                                self.rand.randrange(self.chunk_bytes)):
                    self.event("replica_corrupted", object=name,
                               part=pi, chunk=ci, node=node.node_id)
                    return True
        return False

    # ---- convergence ----

    async def namespace_valid(self) -> bool:
        from chunky_bits_tpu.file import FileIntegrity

        for name in sorted(self.contents):
            try:
                report = await (await self.cluster.get_file_ref(name)
                                ).verify()
            except Exception:  # noqa: BLE001 — an unreadable ref is
                return False  # simply "not Valid yet" for convergence
            if report.integrity() != FileIntegrity.VALID:
                return False
        return True

    async def wait_converged(self, deadline_s: float,
                             check_every_s: float = 60.0) -> bool:
        """Poll the namespace until every object verifies Valid or
        ``deadline_s`` of *virtual* time passes."""
        deadline = self.now() + deadline_s
        while True:
            if await self.namespace_valid():
                self.event("converged")
                return True
            if self.now() >= deadline:
                self.event("converge_deadline_exceeded")
                return False
            await self.sleep(check_every_s)

    # ---- standard verdicts ----

    def check_reads_clean(self) -> None:
        """No client-visible error outside a scripted fault window
        (reads *inside* a window still usually succeed via
        reconstruction — an error there is the scenario's documented
        allowance, not silent breakage)."""
        stray = [(t, name, err) for t, name, err in self._client_errors
                 if not self._in_fault_window(t)]
        self.verdict("reads_clean_outside_fault", not stray,
                     stray=len(stray), total_reads=self.client_reads,
                     in_window=len(self._client_errors) - len(stray))

    def check_hedge_budget(self) -> None:
        """Hedge amplification within the token-bucket bound: fired
        hedges can never exceed ratio x primaries + the burst the
        bucket started with."""
        board = self.cluster.health_scoreboard()
        stats = board.stats()
        bound = (board.hedge_ratio * stats.primaries
                 + board.hedge_burst)
        self.verdict("hedge_within_budget",
                     stats.hedges_fired <= bound,
                     fired=stats.hedges_fired,
                     primaries=stats.primaries,
                     bound=round(bound, 2))

    def check_repair_bytes(self) -> None:
        """The config-11/13 structural bounds, exactly: decode plans
        read d x range bytes, msr plans read d' x beta, copy plans at
        most one chunk off the healthy replica (x2 slack: a replica
        that fails whole-chunk verification — raced writer — may be
        re-read off the next source once).  Helper bytes above the
        structural prediction mean the planner moved bytes nothing
        accounts for."""
        rep = self.scrub_stats().repair or {}
        d = self.d
        ok = True
        decode_b = rep.get("helper_bytes_decode", 0)
        decode_bound = rep.get("plans_decode", 0) * d * self.chunk_bytes
        if decode_b > decode_bound:
            ok = False
        msr_b = rep.get("helper_bytes_msr", 0)
        msr_bound = rep.get("plans_msr", 0) * 2 * self.chunk_bytes
        if msr_b > msr_bound:
            ok = False
        copy_b = rep.get("helper_bytes_replica", 0)
        copy_bound = rep.get("plans_copy", 0) * 2 * self.chunk_bytes
        if copy_b > copy_bound:
            ok = False
        self.verdict("repair_bytes_structural", ok,
                     helper_bytes_decode=decode_b,
                     decode_bound=decode_bound,
                     helper_bytes_msr=msr_b, msr_bound=msr_bound,
                     helper_bytes_replica=copy_b,
                     copy_bound=copy_bound)

    # ---- teardown ----

    async def close(self) -> None:
        await self.stop_client()
        await self.stop_slo()
        await self.stop_scrub()
        await self.cluster.tunables.location_context().aclose()
        self.fabric.close()


# ---- the scenario library ----

async def _az_outage(env: ScenarioEnv) -> None:
    """A full availability zone partitions away mid-scrub, sector
    losses land in the surviving zones, the zone comes back.  Repair
    of partitioned replicas must WAIT the partition out (their bytes
    are intact — no fallback/republish storm rebuilding them
    elsewhere), surviving-zone losses repair in place meanwhile, reads
    stay clean throughout (zone cap = parity), and the namespace
    converges to Valid."""
    fab = env.fabric
    # the operator knows this is an AZ outage, not dead disks: the
    # re-placement escalation is deliberately parked beyond the
    # outage so partitioned replicas are waited for, never moved
    env.start_scrub(replace_after_s=3600.0)
    env.start_client(period_s=5.0)
    await env.sleep(120.0)  # two healthy passes of warmup
    zone = fab.zones[0]
    env.fault_begin()
    env.event("az_outage_begin", zone=zone)
    fab.set_zone_state(zone, fabric_mod.PARTITIONED)
    await env.sleep(600.0)
    # sector losses in the surviving zones while degraded: one per
    # part, so parts stay readable AND repairable in place
    dropped = await env.drop_replicas(6, avoid_zones=(zone,))
    env.event("surviving_zone_losses", dropped=dropped)
    await env.sleep(900.0)
    fab.set_zone_state(zone, fabric_mod.RECOVERING)
    env.event("az_outage_end", zone=zone)
    env.fault_end(grace_s=120.0)
    await env.sleep(300.0)
    await env.stop_client()
    converged = await env.wait_converged(1800.0)
    await env.stop_scrub()
    env.verdict("converged", converged)
    env.check_reads_clean()
    env.check_repair_bytes()
    rep = env.scrub_stats().repair or {}
    # partitioned replicas came back intact: repairing them in place
    # never needed the classic resilver (no republish storm)
    env.verdict("no_fallback_storm",
                rep.get("plans_fallback", 0) == 0,
                plans_fallback=rep.get("plans_fallback", 0))


async def _rolling_restart(env: ScenarioEnv) -> None:
    """A rolling restart sweeps a quarter of the fleet (each node dead
    30 s, then recovering) under client load; no scripted damage, so
    the only acceptable outcome is zero client-visible errors and an
    untouched-Valid namespace."""
    fab = env.fabric
    env.start_scrub()
    env.start_client(period_s=4.0)
    await env.sleep(60.0)
    victims = sorted(fab.nodes)[::4]
    env.event("rolling_restart_begin", nodes=len(victims))
    for node_id in victims:
        node = fab.nodes[node_id]
        node.set_state(fabric_mod.DEAD)
        await env.sleep(30.0)
        node.set_state(fabric_mod.RECOVERING)
        await env.sleep(10.0)
    env.event("rolling_restart_end")
    await env.sleep(120.0)
    await env.stop_client()
    converged = await env.wait_converged(900.0)
    await env.stop_scrub()
    env.verdict("converged", converged)
    # restarts are not faults to the client: d-of-d+p reads ride over
    # any single dead node, so NO window is declared and every read
    # must have stayed clean
    env.check_reads_clean()
    env.check_repair_bytes()


async def _pm_msr_restart_repair(env: ScenarioEnv) -> None:
    """Single-chunk loss on a pm-msr part repaired WHILE a rolling
    restart churns the helper set: the msr plan either completes off
    2(d-1) projections or falls back cleanly to decode — and the
    ``cb_repair_*`` counters carry the pm-msr code label either way."""
    fab = env.fabric
    env.start_scrub()
    env.start_client(period_s=6.0)
    await env.sleep(60.0)
    # whole-chunk loss: every byte of one data chunk of one object
    name = sorted(env.contents)[0]
    for pi, ci, target in await env._locations_of(name):
        if pi == 0 and ci == 0:
            node, chunk_name = fabric_mod.resolve(target)
            node.drop(chunk_name)
            env.event("chunk_lost", object=name, node=node.node_id)
            break
    env.event("rolling_restart_begin")
    victims = sorted(fab.nodes)[::3]
    for node_id in victims:
        node = fab.nodes[node_id]
        node.set_state(fabric_mod.DEAD)
        await env.sleep(20.0)
        node.set_state(fabric_mod.HEALTHY)
    env.event("rolling_restart_end")
    await env.sleep(120.0)
    await env.stop_client()
    converged = await env.wait_converged(1200.0)
    await env.stop_scrub()
    env.verdict("converged", converged)
    rep = (env.scrub_stats().repair or {}).get("by_code", {})
    pm = rep.get("pm-msr", {})
    rs = rep.get("rs", {})
    # every repair this scenario performed belongs to the pm-msr label
    # (the closed-set discipline CB107 pins statically, observed live)
    plans = (pm.get("plans_msr", 0) + pm.get("plans_decode", 0)
             + pm.get("plans_copy", 0) + pm.get("plans_fallback", 0))
    env.verdict("repair_labeled_pm_msr",
                plans >= 1 and rs.get("bytes_rebuilt", 0) == 0,
                pm_plans=plans, plans_msr=pm.get("plans_msr", 0),
                plans_decode=pm.get("plans_decode", 0))
    env.check_reads_clean()
    env.check_repair_bytes()


async def _thundering_herd(env: ScenarioEnv) -> None:
    """Everyone wants the same object while one of its replica nodes
    straggles pathologically: hedges fire, the token-bucket budget
    must cap amplification at ratio x primaries + burst even under a
    herd — and the hedge-exhaustion alert must SEE the bucket pinned
    at its cap (fired/primaries sustained at the budget slope)."""
    fab = env.fabric
    hot = sorted(env.contents)[0]
    # slow a node that actually serves the hot object — and make it a
    # pathological straggler: x400 puts its reads far past the
    # adaptive hedge-delay CEILING (20x the floor), so every read of
    # the hot part is hedge-worthy and the token bucket pins at its
    # cap (a merely-2x-slow node hides under the adaptive p95 — the
    # tail-only hedging the budget design intends)
    locs = await env._locations_of(hot)
    node, _ = fabric_mod.resolve(locs[0][2])
    node.slow_factor = 400.0
    node.set_state(fabric_mod.SLOW)
    # a straggler this bad is a fault the operator declared: reads
    # still succeed (slow, never an error), but the hedge-exhaustion
    # alert belongs to this window
    env.fault_begin(backdate_s=5.0)
    env.event("herd_begin", object=hot, slow_node=node.node_id)

    async def one_reader(i: int) -> None:
        for _ in range(6):
            await env.read_object(hot)
            await env.sleep(1.0 + (i % 7) * 0.25)

    readers = [asyncio.ensure_future(one_reader(i)) for i in range(40)]
    try:
        await asyncio.gather(*readers)
    finally:
        for task in readers:
            task.cancel()
        # cancel() only REQUESTS: when the gather above fails, the
        # surviving readers are still mid-read — without this reap
        # their teardown (hedge latency samples, budget refunds) races
        # into the healthy window below and into the determinism trace
        await asyncio.gather(*readers, return_exceptions=True)
    node.set_state(fabric_mod.HEALTHY)
    env.event("herd_end")
    env.fault_end(grace_s=30.0)
    env.check_reads_clean()  # a stall is slow, never an error
    env.check_hedge_budget()
    board = env.cluster.health_scoreboard().stats()
    env.verdict("herd_reads_served",
                env.client_reads >= 240,
                reads=env.client_reads,
                hedges_fired=board.hedges_fired,
                hedges_won=board.hedges_won)


async def _correlated_failures(env: ScenarioEnv) -> None:
    """A batch of disks in ONE zone dies for good (bytes gone, nodes
    refuse connections): the zone cap guarantees readability, and once
    the victims stay unwritable past the re-placement threshold the
    planner escalates to the classic resilver, which re-places the
    lost chunks on survivors and republishes — the namespace
    converges.  (This scenario is what exposed the planner's original
    retry-in-place-forever gap; cluster/repair.py's
    ``replace_after_s`` is the fix it pins.)"""
    fab = env.fabric
    env.start_scrub(replace_after_s=300.0)
    env.start_client(period_s=5.0)
    await env.sleep(120.0)
    zone = fab.zones[-1]
    victims = sorted(n.node_id for n in fab.nodes_in_zone(zone))[::3]
    env.fault_begin()
    env.event("correlated_failures", zone=zone, nodes=len(victims))
    for node_id in victims:
        node = fab.nodes[node_id]
        node.store.clear()  # the disk is gone, not just the process
        node.set_state(fabric_mod.DEAD)
    env.fault_end(grace_s=60.0)
    await env.sleep(300.0)
    await env.stop_client()
    converged = await env.wait_converged(2400.0)
    await env.stop_scrub()
    env.verdict("converged", converged)
    env.check_reads_clean()
    rep = env.scrub_stats().repair or {}
    env.verdict("replaced_lost_chunks",
                env.scrub_stats().repaired > 0
                or rep.get("plans_fallback", 0) > 0,
                repaired=env.scrub_stats().repaired,
                fallbacks=rep.get("plans_fallback", 0))


async def _flapping_node(env: ScenarioEnv) -> None:
    """A node flaps between erroring and healthy until its breaker
    opens; once the flapping stops, the half-open probe must recover
    it — an open breaker may never strand a live node at zero traffic
    forever."""
    fab = env.fabric
    env.start_client(period_s=2.0)
    # flap a node that actually SERVES the namespace (holder of the
    # first object's first data chunk): at fleet scale an arbitrary
    # node may hold nothing, and "traffic returned" would be vacuous
    locs = await env._locations_of(sorted(env.contents)[0])
    node, _ = fabric_mod.resolve(locs[0][2])
    env.fault_begin()
    env.event("flapping_begin", node=node.node_id)
    for _ in range(10):
        node.set_state(fabric_mod.ERRORING)
        await env.sleep(4.0)
        node.set_state(fabric_mod.HEALTHY)
        await env.sleep(2.0)
    env.event("flapping_end", node=node.node_id)
    env.fault_end(grace_s=30.0)
    ops_at_end_of_flap = node.ops
    # long quiet period under load: the cooldown elapses, a half-open
    # probe lands, the breaker closes, traffic returns
    await env.sleep(600.0)
    await env.stop_client()
    board = env.cluster.health_scoreboard()
    from chunky_bits_tpu.file.location import Location

    probe = Location.sim(f"{fab.fabric_id}/{node.node_id}/probe")
    state = board.breaker_state(probe)
    env.verdict("breaker_recovered",
                state in ("closed", "half-open"),
                breaker=state)
    env.verdict("traffic_returned",
                node.ops > ops_at_end_of_flap,
                ops_during=ops_at_end_of_flap, ops_after=node.ops)
    env.check_reads_clean()


async def _slow_leak(env: ScenarioEnv) -> None:
    """Latent corruption drips in (one flipped byte per scrub
    interval, one chunk per part at a time): continuous scrub must
    detect and repair each before the next lands, reads must stay
    byte-identical throughout (reconstruction covers the window), and
    the namespace ends Valid."""
    env.start_scrub()
    env.start_client(period_s=4.0)
    names = sorted(env.contents)
    for i in range(10):
        name = names[env.rand.randrange(len(names))]
        await env.corrupt_replica(name, part=0,
                                  chunk=env.rand.randrange(env.d))
        await env.sleep(env.scrub_interval_s * 2)
    await env.stop_client()
    converged = await env.wait_converged(1200.0)
    stats = env.scrub_stats()
    await env.stop_scrub()
    env.verdict("converged", converged)
    env.verdict("corruption_detected", stats.corrupt >= 1,
                corrupt=stats.corrupt, repaired=stats.repaired)
    # corruption is exactly what parity exists for: never client-visible
    env.check_reads_clean()
    env.check_repair_bytes()


async def _disk_corruption_storm(env: ScenarioEnv) -> None:
    """The disk-fault axis (PR-14's crash harness at fleet scale): a
    burst of latent corruption lands across many nodes in one scrub
    interval — a bad firmware push, not a single rotting sector —
    while one victim node silently TEARS its next repair writes (acks
    a prefix: the crash harness's torn-write image as a live fleet
    fault) and another refuses writes disk-full for a while.  Scrub
    must detect every rotten replica through the content-address gate,
    repair must ride out torn and refused rewrites (re-detect, retry
    next pass — a torn repair is corruption again, never silent
    success), reads stay byte-identical throughout (reconstruction
    covers every window), and the namespace converges to Valid.  No
    fault window is declared: nothing here may ever be client-visible,
    and the SLO engine must stay silent (precision check)."""
    fab = env.fabric
    env.start_scrub()
    env.start_client(period_s=4.0)
    await env.sleep(90.0)
    names = sorted(env.contents)
    victims = names[:8]
    # the torn-writes node: holder of victims[0] part-0 chunk-0, which
    # we corrupt deliberately so its repair write is the one that tears
    locs = await env._locations_of(victims[0])
    torn_target = [t for pi, ci, t in locs if pi == 0 and ci == 0][0]
    torn_node, _ = fabric_mod.resolve(torn_target)
    torn_node.faults.torn_put_bytes = 64
    torn_node.faults.torn_put_remaining = 2
    # the disk-full node: holder of victims[1] part-0 chunk-1
    locs = await env._locations_of(victims[1])
    full_target = [t for pi, ci, t in locs if pi == 0 and ci == 1][0]
    full_node, _ = fabric_mod.resolve(full_target)
    full_node.faults.put_fail_status = 507
    full_node.faults.put_fail_remaining = 3
    env.event("corruption_storm_begin", victims=len(victims),
              torn_node=torn_node.node_id,
              full_node=full_node.node_id)
    burst = 0
    for i, name in enumerate(victims):
        chunk = (0 if i == 0 else
                 1 if i == 1 else env.rand.randrange(env.d))
        if await env.corrupt_replica(name, part=0, chunk=chunk):
            burst += 1
    env.event("corruption_storm_landed", corrupted=burst)
    # several scrub intervals: detect, repair, re-detect the torn
    # repairs, exhaust the fault budgets, repair for good
    await env.sleep(env.scrub_interval_s * 8)
    await env.stop_client()
    converged = await env.wait_converged(1800.0)
    stats = env.scrub_stats()
    await env.stop_scrub()
    env.verdict("converged", converged)
    env.verdict("corruption_detected", stats.corrupt >= burst,
                corrupt=stats.corrupt, burst=burst,
                repaired=stats.repaired)
    # the scripted disk faults must actually have fired (a vacuously
    # green storm proves nothing)
    env.verdict("torn_writes_ridden_out",
                torn_node.torn_writes >= 1
                and torn_node.faults.torn_put_remaining == 0,
                torn_writes=torn_node.torn_writes)
    env.verdict("disk_full_ridden_out",
                full_node.faults.put_fail_remaining == 0,
                errors_injected=full_node.errors_injected)
    # corruption is exactly what parity exists for: never client-visible
    env.check_reads_clean()
    env.check_repair_bytes()


async def _fleet_partition(env: ScenarioEnv) -> None:
    """Total connectivity loss: every zone partitions away while the
    continuous scrub runs.  The chunk bytes are all intact — the only
    thing wrong is reachability — so the correct repair response is
    NOTHING (re-placement escalation parked beyond the outage), and
    the observability story is the point: the scrub-progress-stall
    rule must detect a daemon that is up but verifying zero bytes, the
    breaker plane must mark the fleet degraded, and both alerts must
    resolve once connectivity returns and the namespace re-verifies
    Valid."""
    fab = env.fabric
    # a 1 s request timeout against unreachable peers (the fabric's
    # default 5 s stall models a patient client; an operator running
    # continuous scrub tightens it): at N=100 a 5 s stall per
    # partitioned read would stretch one scrub pass past the whole
    # outage, and the breaker plane would see too few consecutive
    # failures per node to trip before the heal
    for node in fab.nodes.values():
        node.partition_stall_s = 1.0
    env.start_scrub(replace_after_s=36000.0)
    # warm passes: the stall rule needs a progressing baseline first
    await env.sleep(180.0)
    env.fault_begin()
    env.event("fleet_partition_begin")
    for zone in fab.zones:
        fab.set_zone_state(zone, fabric_mod.PARTITIONED)
    await env.sleep(900.0)
    for zone in fab.zones:
        fab.set_zone_state(zone, fabric_mod.RECOVERING)
    env.event("fleet_partition_end")
    env.fault_end(grace_s=120.0)
    # post-heal scrub passes BEFORE stopping: breakers only recover on
    # traffic (a half-open probe needs a request to ride), and the
    # scrub walk is the traffic source this clientless scenario has —
    # exactly the operational reason a real fleet keeps scrub running
    # after an outage
    await env.sleep(300.0)
    converged = await env.wait_converged(1500.0)
    await env.stop_scrub()
    env.verdict("converged", converged)
    env.check_repair_bytes()


async def _noisy_neighbor(env: ScenarioEnv) -> None:
    """One antagonist tenant floods the read plane while a victim
    issues periodic reads — the multi-tenant QoS claim, proven
    deterministically.  THREE phases share one virtual timeline:

    1. **baseline** — the victim reads alone (no flood, no admission):
       its unloaded latency, the yardstick;
    2. **FIFO leg (QoS off)** — admission is a plain FIFO semaphore
       (the pre-QoS gateway shape): the victim's reads queue behind
       the whole antagonist backlog;
    3. **DRR leg (QoS on)** — the SAME flood through the production
       :class:`~chunky_bits_tpu.cluster.qos.QosScheduler` (the exact
       class the gateway runs, here in virtual time): deficit
       round-robin rotates tenants, so the victim waits out roughly
       one rotation regardless of the antagonist backlog.

    Verdicts: the victim's p99 under DRR stays within a small factor
    of baseline AND beats the FIFO leg by the isolation factor; the
    flood itself never produces a client-visible error (reads-clean,
    no fault windows at all); the SLO engine stays silent throughout
    (precision — an antagonist tenant is load, not an outage)."""
    from chunky_bits_tpu.cluster.qos import QosConfig, QosScheduler

    capacity = 8
    antagonists = 48
    victim_reads = 10
    #: virtual body-streaming time per read while the admission slot
    #: is held — the service time queue waits are measured against
    #: (the fabric's per-chunk fetch latencies are sub-millisecond at
    #: this scale; a real GET holds its slot for the whole body)
    service_s = 0.2
    names = sorted(env.contents)

    async def victim_pass(tag: str, acquire, release) -> float:
        """The victim's periodic reads through one admission shape;
        returns its p99 (max at this sample count) acquire-to-done
        latency in virtual seconds."""
        lat: list[float] = []
        for k in range(victim_reads):
            t0 = env.now()
            await acquire("victim")
            try:
                await env.read_object(names[k % len(names)])
                await env.sleep(service_s)
            finally:
                release()
            lat.append(env.now() - t0)
            await env.sleep(0.1)
        lat.sort()
        p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]
        env.event("victim_pass", leg=tag, p99_s=round(p99, 6),
                  reads=len(lat))
        return p99

    async def flooded_pass(tag: str, acquire, release) -> float:
        """victim_pass with the antagonist flood running: every
        antagonist keeps one read permanently queued or in flight."""
        stop = False

        async def antagonist(i: int) -> None:
            while not stop:
                await acquire("antagonist")
                try:
                    await env.read_object(names[i % len(names)])
                    await env.sleep(service_s)
                finally:
                    release()

        tasks = [asyncio.ensure_future(antagonist(i))
                 for i in range(antagonists)]
        # let the flood saturate admission before the victim arrives
        await env.sleep(2.0)
        try:
            return await victim_pass(tag, acquire, release)
        finally:
            stop = True
            for task in tasks:
                task.cancel()
            # reap before the next leg: a surviving antagonist would
            # race its teardown into the other leg's latencies and the
            # determinism trace
            await asyncio.gather(*tasks, return_exceptions=True)

    # phase 1: unloaded baseline (admission is a no-op)
    async def no_acquire(tenant: str) -> None:
        return None

    baseline_p99 = await victim_pass("baseline", no_acquire,
                                     lambda: None)

    # phase 2: QoS off — FIFO admission, one global line
    sem = asyncio.Semaphore(capacity)

    async def fifo_acquire(tenant: str) -> None:
        # lint: lock-discipline-ok acquire/release are a paired
        # callable handed to victim_pass/flooded_pass, which releases
        # in its finally — the pairing spans the closure boundary
        await sem.acquire()

    fifo_p99 = await flooded_pass("fifo", fifo_acquire, sem.release)

    # phase 3: QoS on — the production scheduler, weighted victim
    config = QosConfig.from_obj({
        "tenants": {
            "victim": {"weight": 4, "keys": ["victim-key"]},
            "antagonist": {"keys": ["antagonist-key"]},
        },
    })
    sched = QosScheduler(config, read_capacity=capacity,
                         write_capacity=2, queue_timeout_s=120.0)

    async def drr_acquire(tenant: str) -> None:
        # lint: lock-discipline-ok acquire/release are a paired
        # callable handed to flooded_pass, which releases in its
        # finally — the pairing spans the closure boundary
        await sched.acquire("read", tenant, cost=env.object_bytes)

    drr_p99 = await flooded_pass("drr", drr_acquire,
                                 lambda: sched.release("read"))

    qos = sched.stats()
    env.event("noisy_neighbor_done",
              baseline_p99_s=round(baseline_p99, 6),
              fifo_p99_s=round(fifo_p99, 6),
              drr_p99_s=round(drr_p99, 6),
              qos_pressure_peak=round(qos.pressure, 4),
              victim_admitted=qos.to_obj()["tenants"]["victim"]
              ["admitted"])
    # isolation: DRR holds the victim near its unloaded latency (one
    # rotation of slack) where FIFO queues it behind the whole flood
    env.verdict("victim_isolated_under_drr",
                drr_p99 <= fifo_p99 / 3.0,
                fifo_p99_s=round(fifo_p99, 6),
                drr_p99_s=round(drr_p99, 6))
    env.verdict("victim_near_baseline_under_drr",
                drr_p99 <= max(baseline_p99 * 8.0, baseline_p99 + 1.0),
                baseline_p99_s=round(baseline_p99, 6),
                drr_p99_s=round(drr_p99, 6))
    # the flood must actually have been a flood: FIFO visibly degraded
    # the victim, else both legs trivially pass
    env.verdict("fifo_leg_degraded",
                fifo_p99 > baseline_p99 * 2.0,
                baseline_p99_s=round(baseline_p99, 6),
                fifo_p99_s=round(fifo_p99, 6))
    env.check_reads_clean()  # contention is slow, never an error


@dataclass(frozen=True)
class Scenario:
    name: str
    driver: Callable[[ScenarioEnv], Awaitable[None]]
    #: ScenarioEnv overrides (geometry, knobs) this scenario needs
    env: dict
    #: SLO detection spec (ScenarioEnv.start_slo): which alerts MUST
    #: fire (with detection bounds), objective overrides, tick cadence.
    #: Empty = pure precision check — the engine still runs and ZERO
    #: alerts may fire.
    slo: dict = field(default_factory=dict)


SCENARIOS: dict[str, Scenario] = {
    s.name: s for s in (
        # a third of the fleet partitions: the breaker plane must mark
        # it degraded (fraction over the 0.3 objective) within the
        # persistence window, and recover once the zone returns.  The
        # detection bound tracks fleet-scale physics: each partitioned
        # node trips after 5 consecutive failures, accumulated at the
        # scrub pass cadence, and partitioned reads stall 5 s each —
        # at N=100 one pass spans several virtual minutes, so the
        # fraction crosses the objective a few passes into the outage
        Scenario("az_outage", _az_outage, {
            "scrub_bytes_per_sec": 50e6, "scrub_interval_s": 60.0,
        }, slo={
            "expected": {"breaker_open": {"within_s": 1500.0,
                                          "resolve": True}},
            "objectives": {"breaker_node_fraction": 0.3},
        }),
        # restarts are routine, not faults: the engine must stay
        # SILENT through a quarter-fleet rolling restart (precision)
        Scenario("rolling_restart", _rolling_restart, {
            "scrub_bytes_per_sec": 50e6, "scrub_interval_s": 120.0,
        }),
        Scenario("pm_msr_restart_repair", _pm_msr_restart_repair, {
            "data": 5, "parity": 4, "code": "pm-msr",
            "objects": 8,
            "scrub_bytes_per_sec": 50e6, "scrub_interval_s": 90.0,
        }),
        # a herd against a straggler pins the hedge token bucket at
        # its cap: the hedge-exhaustion rule must see fired/primaries
        # at the budget slope (tight windows — the herd lives seconds)
        Scenario("thundering_herd", _thundering_herd, {
            "hedge_ms": 25.0, "objects": 8,
        }, slo={
            "expected": {"hedge_exhaustion": {"within_s": 60.0,
                                              "resolve": True}},
            "objectives": {"fast_s": 5.0, "slow_s": 10.0,
                           "clear_s": 10.0},
            "tick_s": 1.0,
        }),
        # disks die for good: the planner's re-placement escalation IS
        # the repair-fallback-storm signal (resolves once re-placed);
        # the dead zone is ~a tenth of the fleet, so breaker_open must
        # NOT fire at the 0.3 objective
        Scenario("correlated_failures", _correlated_failures, {
            "scrub_bytes_per_sec": 50e6, "scrub_interval_s": 90.0,
        }, slo={
            "expected": {"repair_fallback_storm": {"within_s": 900.0,
                                                   "resolve": True}},
        }),
        # one flapping node of many: below every fraction objective —
        # the engine must stay silent while the breaker does its job
        Scenario("flapping_node", _flapping_node, {
            "objects": 12,
        }),
        # latent corruption drips in and scrub keeps up: progress
        # never stalls, no storms — silence is the correct verdict
        Scenario("slow_leak", _slow_leak, {
            "scrub_bytes_per_sec": 50e6, "scrub_interval_s": 45.0,
        }),
        # the disk-fault axis: a corruption burst plus torn and
        # refused repair writes — all absorbed by scrub/repair, never
        # client-visible, SLO engine silent (precision check)
        Scenario("disk_corruption_storm", _disk_corruption_storm, {
            "scrub_bytes_per_sec": 50e6, "scrub_interval_s": 45.0,
            "objects": 12,
        }),
        # total connectivity loss: scrub-progress stall, fleet-wide
        # breaker degradation, AND the planner's fallback storm (every
        # pass hands every unreachable part back to the classic
        # resilver) — all three detected, all three resolving after
        # the heal
        # an antagonist tenant floods reads: load, not an outage — the
        # engine must stay silent (precision) while the QoS verdicts
        # prove weighted-fair isolation of the victim tenant
        Scenario("noisy_neighbor", _noisy_neighbor, {
            "objects": 8,
        }),
        Scenario("fleet_partition", _fleet_partition, {
            "scrub_bytes_per_sec": 50e6, "scrub_interval_s": 60.0,
        }, slo={
            "expected": {
                "scrub_stall": {"within_s": 600.0, "resolve": True},
                "breaker_open": {"within_s": 600.0, "resolve": True},
                "repair_fallback_storm": {"within_s": 300.0,
                                          "resolve": True},
            },
            "objectives": {"scrub_stall_s": 240.0},
        }),
    )
}


def run_scenario(name: str, *, nodes: int = 100, seed: int = 0,
                 workdir: str, objects: Optional[int] = None
                 ) -> ScenarioResult:
    """Run one library scenario to completion on a fresh virtual-time
    loop and a fresh metrics registry; returns the result row.  Wall
    time is measured on the always-real system clock (the virtual
    clock is installed process-wide for the duration)."""
    scenario = SCENARIOS.get(name)
    if scenario is None:
        raise ValueError(
            f"unknown scenario {name!r} (know {sorted(SCENARIOS)})")
    env_kwargs = dict(scenario.env)
    if objects is not None:
        env_kwargs["objects"] = objects
    real = _clock.system_clock()
    wall0 = real.monotonic()
    # warm the process-shared host pipeline BEFORE the registry swap:
    # it self-registers at construction, and its busy/idle counters
    # are wall-clock seconds — they belong to the production registry,
    # never to a scenario's deterministic snapshot
    from chunky_bits_tpu.parallel.host_pipeline import get_host_pipeline

    get_host_pipeline()
    previous_registry = obs_metrics.swap_registry(
        obs_metrics.MetricsRegistry())
    # ScenarioEnv reseeds the process-global `random` (the read/write
    # paths' jitter draws must replay run-to-run); bracket it so the
    # reseed cannot leak determinism into whatever runs after us in
    # the same process (later tests, other bench legs)
    previous_random_state = random.getstate()

    async def main() -> tuple[ScenarioEnv, float, dict]:
        env = ScenarioEnv(name, workdir, nodes=nodes, seed=seed,
                          **env_kwargs)
        try:
            env.event("scenario_begin", scenario=name, nodes=nodes,
                      seed=seed)
            await env.write_namespace()
            # EVERY scenario runs the SLO engine — scenarios with no
            # `slo` spec are precision runs (zero alerts may fire);
            # started after the namespace write so the warmup I/O burst
            # is not part of the observed story, before the driver so
            # the quiet period ahead of the fault is
            env.start_slo(scenario.slo)
            await scenario.driver(env)
            await env.settle_slo()
            env.check_slo()
            env.event("scenario_end", scenario=name)
            virtual = env.now()
            metrics = obs_metrics.get_registry().snapshot()
            return env, virtual, metrics
        finally:
            await env.close()

    try:
        env, virtual, metrics = sim_loop.run(main())
    finally:
        obs_metrics.swap_registry(previous_registry)
        random.setstate(previous_random_state)
    return ScenarioResult(
        name=name, seed=seed, nodes=nodes,
        virtual_seconds=virtual,
        wall_seconds=real.monotonic() - wall0,
        trace=env.trace.to_bytes(),
        metrics=metrics,
        verdicts=dict(env.verdicts),
        details={"client_reads": env.client_reads,
                 "fabric": env.fabric.stats(),
                 "slo": env.slo_report()},
    )


def fresh_workdir(path: str) -> str:
    """Reset ``path`` to an empty directory (determinism runs reuse
    one path so metadata locations are string-identical run to run)."""
    import os

    shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path)
    return path
