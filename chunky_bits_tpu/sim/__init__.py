"""Deterministic cluster simulator.

Thousand-node fault scenarios in compressed virtual time, with the
metrics registry as the observer (ROADMAP item 5 — the
scenario-diversity axis of the north star).  Three coordinated pieces:

* ``sim/loop.py`` — a virtual-time asyncio event loop: when nothing is
  runnable and no host-thread work is in flight, time jumps straight to
  the next timer, so a 60-minute scrub pass runs in milliseconds of
  wall time.  ``sim.run(coro)`` is the entry point: it builds the loop,
  installs a :class:`chunky_bits_tpu.utils.clock.VirtualClock` through
  the process-wide clock seam (``cluster/clock.py``), and tears both
  down asyncio.run-style (no leaked tasks — the SANITIZE=1 contract).
* ``sim/fabric.py`` — the fault-injection node plane: in-process
  simulated storage nodes behind the existing ``Location`` surface
  (the ``sim:`` kind — the same lazy-dispatch trick as ``slab:``),
  each with a distribution-driven latency model (lognormal body +
  configurable tail), a fault state machine (healthy → slow → erroring
  → partitioned → dead → recovering), zone topology, and byte-accounted
  virtual bandwidth.
* ``sim/scenario.py`` — the scenario engine: scripted timelines (AZ
  outage, rolling restart, thundering herd, correlated disk failures,
  flapping node, slow-leak corruption) over a generated namespace,
  asserting convergence invariants and emitting a seed-reproducible
  event trace + metrics snapshot (same seed ⇒ byte-identical trace —
  pinned by tests/test_sim.py).

Production code paths import NOTHING from this package: the clock seam
defaults to the system clock, and ``file/location.py``'s ``sim:``
branches import ``sim.fabric`` lazily, only when a sim location is
actually touched (exactly like the ``slab:`` branches and
``file/slab.py``).  Bench ``--config 14`` is the scenario-suite runner.
"""

from chunky_bits_tpu.sim.loop import VirtualTimeLoop, run  # noqa: F401

__all__ = ["VirtualTimeLoop", "run"]
