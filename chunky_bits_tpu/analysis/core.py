"""Analyzer plumbing: file model, suppressions, baseline, runner.

Everything here is stdlib-only (``ast`` + ``tokenize`` + ``hashlib``).
The TOML baseline is read with ``tomllib`` (3.11+) or ``tomli`` when
present, with a minimal fallback parser for the restricted subset this
module itself emits — the gate must run on a bare interpreter.
"""

from __future__ import annotations

import ast
import hashlib
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator, Optional

#: inline suppression: ``# lint: <slug>-ok <reason>`` — the reason is
#: mandatory (a bare marker does not suppress).  On a comment-only line
#: the marker covers the next line; trailing markers cover their own.
_SUPPRESS_RE = re.compile(r"#\s*lint:\s*([a-z0-9-]+)-ok\b[ \t]*(.*)")

#: ``# noqa: BLE001 <text>`` is accepted as a broad-except justification
#: (one pre-existing site already uses the flake8-bugbear spelling).
_NOQA_BLE_RE = re.compile(r"#\s*noqa:\s*BLE001\b[ \t]*[-—:]?[ \t]*(.*)")


@dataclass(frozen=True)
class Violation:
    """One finding.  ``fingerprint`` identifies it across unrelated
    edits: it hashes the rule, the file, the enclosing def/class
    qualname, the stripped source line text, and the occurrence index
    of that text *within that scope* — never the line number — so a
    baseline survives both code motion AND duplicate-line churn (an
    identical line added in a DIFFERENT function no longer shifts this
    one's occurrence index).  ``legacy_fingerprint`` is the pre-scope
    spelling (no qualname, file-wide occurrence): baselines written
    before the scheme change still match through it, giving existing
    ``baseline.toml`` files a one-shot migration path — regenerate
    with ``--write-baseline`` to move onto scoped fingerprints."""

    rule: str
    slug: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    snippet: str
    fingerprint: str
    scope: str = ""  # enclosing def/class qualname ('' = module level)
    legacy_fingerprint: str = ""

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.fingerprint)

    def keys(self) -> tuple[tuple[str, str, str], ...]:
        """Every baseline key this finding matches: the scoped
        fingerprint plus the legacy spelling (migration path)."""
        if not self.legacy_fingerprint:
            return (self.key(),)
        return (self.key(),
                (self.rule, self.path, self.legacy_fingerprint))

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.slug}] {self.message}")


class SourceFile:
    """One parsed module plus the comment-derived side tables rules
    need: inline suppressions and module-level string constants (env
    var names travel as constants, e.g. ``DISPATCH_TIMEOUT_ENV``)."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        #: line -> {slug: reason}; a marker on a comment-only line is
        #: registered for that line AND the next
        self.suppressions: dict[int, dict[str, str]] = {}
        self._scan_comments()
        self.constants: dict[str, str] = {}
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                self.constants[node.targets[0].id] = node.value.value
        #: (start, end, qualname) line intervals of every def/class,
        #: for scope-qualified fingerprints; built once, sorted by
        #: (start, -end) so a linear scan finds the innermost match
        self._scopes = self._scope_intervals(self.tree)

    @staticmethod
    def _scope_intervals(tree: ast.AST) -> list[tuple[int, int, str]]:
        out: list[tuple[int, int, str]] = []

        def visit(node: ast.AST, quals: tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    q = quals + (child.name,)
                    out.append((child.lineno,
                                child.end_lineno or child.lineno,
                                ".".join(q)))
                    visit(child, q)
                else:
                    visit(child, quals)

        visit(tree, ())
        out.sort(key=lambda iv: (iv[0], -iv[1]))
        return out

    def scope_qualname(self, line: int) -> str:
        """Qualname of the innermost def/class containing ``line``
        ('' for module level).  Decorator lines belong to the scope
        ABOVE the decorated def — same as how the finding reads."""
        best = ""
        for start, end, qualname in self._scopes:
            if start > line:
                break
            if line <= end:
                best = qualname  # later intervals start deeper
        return best

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.text).readline)
            comments = [(t.start[0], t.start[1], t.string)
                        for t in tokens if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = []
        for line, col, comment in comments:
            entries: dict[str, str] = {}
            m = _SUPPRESS_RE.search(comment)
            if m and m.group(2).strip():
                entries[m.group(1)] = m.group(2).strip()
            m = _NOQA_BLE_RE.search(comment)
            if m and m.group(1).strip():
                entries["broad-except"] = m.group(1).strip()
            if not entries:
                continue
            own_line = self.lines[line - 1] if line <= len(self.lines) \
                else ""
            targets = [line]
            if own_line.strip().startswith("#"):
                # comment-only line: the marker covers the next CODE
                # line, skipping continuation comment/blank lines so a
                # justification may wrap
                nxt = line + 1
                while nxt <= len(self.lines):
                    stripped = self.lines[nxt - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    nxt += 1
                targets.append(nxt)
            for ln in targets:
                self.suppressions.setdefault(ln, {}).update(entries)

    def suppressed(self, slug: str, line: int) -> bool:
        return slug in self.suppressions.get(line, {})

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


def _fingerprint(rule: str, rel: str, snippet: str, occurrence: int,
                 scope: Optional[str] = None) -> str:
    """Scoped fingerprint when ``scope`` is given (the current scheme);
    the legacy no-scope spelling otherwise (kept so pre-migration
    baselines still match — see Violation.keys)."""
    if scope is None:
        basis = f"{rule}\x00{rel}\x00{snippet}\x00{occurrence}"
    else:
        basis = (f"{rule}\x00{rel}\x00{scope}\x00{snippet}"
                 f"\x00{occurrence}")
    return hashlib.sha256(basis.encode("utf-8")).hexdigest()[:16]


def iter_python_files(root: Path) -> Iterator[Path]:
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        yield path


def run_analysis(root: Path, rules: Iterable[object],
                 files: Optional[Iterable[Path]] = None,
                 stats: Optional[dict] = None
                 ) -> tuple[list[Violation], list[str]]:
    """Run ``rules`` over every ``*.py`` under ``root`` (or the explicit
    ``files``).  Returns ``(violations, errors)`` — a file that fails to
    parse is an *error*, not a silent skip: the gate must not go green
    because the tree stopped being parseable.

    Two rule shapes: per-file rules implement ``check(sf)``; *project*
    rules (``rule.project`` truthy: CB204, the CB3xx family) implement
    ``check_project(sfs, ctx)`` over every parsed file at once, sharing
    ONE :class:`~chunky_bits_tpu.analysis.reachability.ProjectContext`
    (call graph + memoized reachability) so the interprocedural pass
    parses and links the tree exactly once per run.  Both shapes feed
    the same suppression, fingerprint, and baseline machinery.

    Pass a dict as ``stats`` to receive call-graph statistics
    (functions/edges/worker_roots/unknown_edges) — forces the graph to
    build even when no project rule is selected (the CLI's
    ``--graph-stats``)."""
    root = root.resolve()
    violations: list[Violation] = []
    errors: list[str] = []
    paths = list(files) if files is not None else \
        list(iter_python_files(root))
    sources: list[SourceFile] = []
    for path in paths:
        path = path.resolve()
        try:
            rel = path.relative_to(root).as_posix()
        except ValueError:
            # outside the root the rel path (which rule scopes and
            # baseline entries key off) cannot resolve; scanning under
            # a basename would silently skip every path-scoped rule
            # and report a false "ok"
            errors.append(f"{path}: outside --root {root}; pass a "
                          f"--root containing it")
            continue
        try:
            text = path.read_text(encoding="utf-8")
            sources.append(SourceFile(path, rel, text))
        except (OSError, SyntaxError, ValueError) as err:
            errors.append(f"{rel}: unreadable/unparseable: {err}")
            continue
    by_rel = {sf.rel: sf for sf in sources}
    # raw findings bucketed per file so fingerprint occurrence indices
    # stay per-file regardless of which rule shape produced them
    raw_by_rel: dict[str, list[tuple[object, int, int, str]]] = \
        {sf.rel: [] for sf in sources}
    per_file = [r for r in rules if not getattr(r, "project", False)]
    project = [r for r in rules if getattr(r, "project", False)]
    for sf in sources:
        for rule in per_file:
            if not rule.applies(sf.rel):
                continue
            for line, col, message in rule.check(sf):
                if sf.suppressed(rule.slug, line):
                    continue
                raw_by_rel[sf.rel].append((rule, line, col, message))
    ctx = None
    if project or stats is not None:
        # one shared context: every project rule reuses the same graph
        from chunky_bits_tpu.analysis.reachability import ProjectContext
        ctx = ProjectContext(sources)
    for rule in project:
        for rel, line, col, message in rule.check_project(sources, ctx):
            sf = by_rel.get(rel)
            if sf is None or not rule.applies(rel) \
                    or sf.suppressed(rule.slug, line):
                continue
            raw_by_rel[rel].append((rule, line, col, message))
    if stats is not None and ctx is not None:
        stats.update(ctx.graph.stats())
        stats.update(ctx.cfg_stats())
    for sf in sources:
        raw = raw_by_rel[sf.rel]
        # occurrence index among same (rule, scope, snippet) triples in
        # line order keeps fingerprints stable under unrelated edits
        # AND under duplicate-line churn in other scopes; the legacy
        # (rule, snippet) counter feeds pre-migration baseline keys
        raw.sort(key=lambda item: (item[1], item[2]))
        seen: dict[tuple[str, str, str], int] = {}
        seen_legacy: dict[tuple[str, str], int] = {}
        for rule, line, col, message in raw:
            snippet = sf.line_text(line)
            scope = sf.scope_qualname(line)
            occ = seen.get((rule.id, scope, snippet), 0)
            seen[(rule.id, scope, snippet)] = occ + 1
            locc = seen_legacy.get((rule.id, snippet), 0)
            seen_legacy[(rule.id, snippet)] = locc + 1
            violations.append(Violation(
                rule=rule.id, slug=rule.slug, path=sf.rel, line=line,
                col=col, message=message, snippet=snippet,
                fingerprint=_fingerprint(rule.id, sf.rel, snippet, occ,
                                         scope=scope),
                scope=scope,
                legacy_fingerprint=_fingerprint(rule.id, sf.rel,
                                                snippet, locc)))
    return violations, errors


# ---- baseline file (analysis/baseline.toml) ----

def write_baseline(path: Path, violations: Iterable[Violation]) -> None:
    out = [
        "# Accepted pre-existing findings — the analyzer fails only on",
        "# NEW violations.  Regenerate with:",
        "#   python -m chunky_bits_tpu.analysis --write-baseline",
        "# Entries are (rule, path, fingerprint); line/scope/summary",
        "# are informational (as of writing) and ignored on load.",
        "# Fingerprints are scope-qualified (rule, path, enclosing",
        "# qualname, line text, in-scope occurrence); entries written",
        "# by older versions still match through the legacy no-scope",
        "# spelling until regenerated.",
        "",
    ]
    for v in sorted(violations, key=lambda v: (v.path, v.line, v.rule)):
        out.append("[[violation]]")
        out.append(f'rule = "{v.rule}"')
        out.append(f'path = "{v.path}"')
        out.append(f'fingerprint = "{v.fingerprint}"')
        out.append(f"line = {v.line}")
        if v.scope:
            out.append(f'scope = "{_toml_escape(v.scope)}"')
        out.append(f'summary = "{_toml_escape(v.message)}"')
        out.append("")
    path.write_text("\n".join(out), encoding="utf-8")


def _toml_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def load_baseline(path: Path) -> set[tuple[str, str, str]]:
    """Accepted-violation keys from the baseline file; an absent file is
    an empty baseline.  A file that exists but does not parse raises
    ``ValueError`` with a clean diagnostic — a hand-edit typo must fail
    the gate loudly, not as a raw decoder traceback (and never silently
    shrink the accepted set)."""
    if not path.exists():
        return set()
    text = path.read_text(encoding="utf-8")
    try:
        data = _parse_toml(text)
    except Exception as err:
        raise ValueError(f"baseline {path}: unparseable TOML: {err}") \
            from err
    keys = set()
    for entry in data.get("violation", []):
        try:
            keys.add((str(entry["rule"]), str(entry["path"]),
                      str(entry["fingerprint"])))
        except KeyError:
            continue
    return keys


def _parse_toml(text: str) -> dict:
    try:
        import tomllib  # Python 3.11+
        return tomllib.loads(text)
    except ImportError:
        pass
    try:
        import tomli
        return tomli.loads(text)
    except ImportError:
        pass
    return _parse_minimal_toml(text)


def _parse_minimal_toml(text: str) -> dict:
    """Fallback parser for exactly the subset ``write_baseline`` emits:
    ``[[violation]]`` tables of ``key = "string"`` / ``key = int``
    lines.  Not a general TOML parser and not meant to be."""
    data: dict = {}
    current: Optional[dict] = None
    for rawline in text.splitlines():
        line = rawline.strip()
        if not line or line.startswith("#"):
            continue
        m = re.fullmatch(r"\[\[([A-Za-z0-9_-]+)\]\]", line)
        if m:
            current = {}
            data.setdefault(m.group(1), []).append(current)
            continue
        m = re.fullmatch(r'([A-Za-z0-9_-]+)\s*=\s*"(.*)"', line)
        if m and current is not None:
            current[m.group(1)] = (m.group(2)
                                   .replace('\\"', '"')
                                   .replace("\\\\", "\\"))
            continue
        m = re.fullmatch(r"([A-Za-z0-9_-]+)\s*=\s*(-?\d+)", line)
        if m and current is not None:
            current[m.group(1)] = int(m.group(2))
    return data
