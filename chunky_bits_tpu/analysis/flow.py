"""CB3xx — whole-program reachability rules.

The CB1xx/CB2xx families see one function or one handoff at a time;
this family checks the invariants that are *reachability* properties,
over the shared function-granular call graph (``callgraph.py``) and the
per-run :class:`~chunky_bits_tpu.analysis.reachability.ProjectContext`:

- CB301 ``fsio-escape``  — the crash harness (sim/crash.py) can only
  replay mutations that ride the filesystem seam.  CB109 pins the five
  storage modules by *path*; this rule closes the hole CB109 cannot
  see: a helper in ``utils/`` (or anywhere) that performs a
  durability op off-seam while being transitively reachable from a
  durability root — slab append/mark-dead/compact, atomic chunk
  publication, metadata write, the repair rewrite.
- CB302 ``clock-escape`` — the deterministic simulator swaps the clock
  seam; CB108 pins the cluster/file planes by path.  This rule follows
  the scenario roots (every function in sim/scenario.py) through the
  graph and flags direct wall-clock reads in reachable code OUTSIDE
  CB108's path list — the exact shape that would tick in real time
  inside a virtual-time run and silently skew every duration.
- CB303 ``cancel-safety`` — three cancellation hazards in async defs:
  (a) a handler that catches ``CancelledError`` (explicitly, via
  ``BaseException``, or bare) around awaits and never re-raises — the
  coroutine absorbs its own cancellation and teardown hangs; the
  sanctioned child-reap shape (``task.cancel()`` then ``await task``
  under the handler) passes.  (b) ``task.cancel()`` on a task variable
  never followed by an await/gather that observes it — the task may
  still be running (and holding locks/files) when the cancelling
  coroutine moves on; the sanitizer sees the leak only at runtime.
  (c) an await between a finished write and its ``replace`` in a
  publish-shaped function — a cancellation delivered there strands the
  temp file and loses the atomic-publish guarantee unless shielded.
- CB304 ``sim-purity``   — production planes import NOTHING from
  ``sim/`` (CLAUDE.md); the subprocess pin in tests/test_sim.py proves
  it at runtime for the *default* import closure, this rule proves it
  statically for every module and every lazy in-function import.
- CB305 ``label-flow``   — CB107 judges ``.labels()`` arguments
  lexically, so a label fed from a function *parameter* passes even
  when every caller passes an f-string.  This rule follows the
  parameter one call hop to the call sites recorded in the graph and
  applies CB107's open-endedness test to the actual arguments.

Same suppression machinery as every other family:
``# lint: <slug>-ok <reason>`` at the flagged line.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from chunky_bits_tpu.analysis.callgraph import attr_chain, iter_body_nodes
from chunky_bits_tpu.analysis.rules import (
    ClockSeamRule,
    Finding,
    FsioSeamRule,
    MetricLabelCardinalityRule,
    Rule,
    _parents,
)

#: the durability roots: the operations whose op streams the crash
#: harness records and replays.  Specs are (rel, qualname-suffix) — see
#: reachability.ProjectContext.resolve_roots; "write" roots every write
#: method in cluster/metadata.py (both metadata shapes publish).
DURABILITY_ROOTS = (
    ("file/slab.py", "SlabStore.append"),
    ("file/slab.py", "SlabStore.mark_dead"),
    ("file/slab.py", "SlabStore.compact"),
    ("file/location.py", "_publish_atomically"),
    ("cluster/metadata.py", "write"),
    ("cluster/repair.py", "repair_part"),
    ("cluster/scrub.py", "_rewrite_replicas"),
)

#: modules where durability ops are already governed (CB109's path
#: scope) or ARE the seam — CB301 flagging there would demand a second
#: suppression for the same site
_FSIO_GOVERNED = FsioSeamRule.paths + ("file/fsio.py", "utils/fsio.py")

#: modules where clock reads are already governed (CB108's path scope)
#: or ARE the seam / the simulator itself
_CLOCK_GOVERNED = ClockSeamRule.paths + (
    "cluster/clock.py", "utils/clock.py", "sim/", "analysis/")


def _durability_op(call: ast.Call, helper: FsioSeamRule
                   ) -> Optional[str]:
    """Description of a durability-relevant op performed by ``call``
    (an ``os.<verb>`` from CB109's verb list, or a write-mode builtin
    ``open``), else None."""
    chain = attr_chain(call.func)
    if chain.startswith("os."):
        verb = chain[3:].split(".", 1)[0]
        if verb in helper.OS_VERBS:
            return f"{chain}()"
        return None
    if chain == "open":
        mode = helper._mode_of(call)
        if any(c in mode for c in "wax+"):
            return f"write-mode open({mode!r})"
    return None


class FsioEscapeRule(Rule):
    """CB301 — no durability op off-seam anywhere a durability root can
    reach.

    CLAUDE.md: "Crash consistency is machine-proven, not prose" — the
    harness replays the op stream ``file/fsio.py`` records, so a
    mutation that bypasses the seam is invisible to every crash-at-op-k
    image.  CB109 guards the five storage modules by path; this rule
    walks the call graph from the durability roots and applies the same
    test to every *reachable* function in every other module, so a
    refactor that extracts ``os.replace`` into a utils/ helper cannot
    silently step off the seam.  Fix: route the op through
    ``fsio.open/replace/fsync/...``; a deliberate off-seam site
    records why with ``# lint: fsio-escape-ok <reason>``.
    """

    id = "CB301"
    slug = "fsio-escape"
    description = ("durability-root-reachable code must do filesystem "
                   "mutations through the file/fsio.py seam")
    project = True

    def check(self, sf) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError("project rule: use check_project")

    def check_project(self, sfs, ctx) -> Iterator[tuple]:
        helper = FsioSeamRule()
        roots = ctx.resolve_roots(DURABILITY_ROOTS)
        if not roots:
            return
        for info in ctx.reachable_infos(roots):
            rel = info.rel
            if rel.startswith(_FSIO_GOVERNED) \
                    or rel.startswith("analysis/"):
                continue
            for node in iter_body_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                desc = _durability_op(node, helper)
                if desc is not None:
                    yield (rel, node.lineno, node.col_offset,
                           f"{desc} in {info.qualname}() is reachable "
                           "from a durability root (slab append/"
                           "compact, publish, metadata write, repair "
                           "rewrite) but bypasses the filesystem seam "
                           "— the crash harness cannot record or "
                           "replay it; route through file/fsio.py or "
                           "justify with `# lint: fsio-escape-ok "
                           "<reason>`")


class ClockEscapeRule(Rule):
    """CB302 — no wall-clock read anywhere a sim scenario can reach.

    The simulator's whole contract (CLAUDE.md sim plane: "same seed ⇒
    byte-identical trace") holds only if every duration on a
    scenario-reachable path resolves through the clock seam.  CB108
    polices ``cluster/``, ``file/``, ``ops/batching.py`` and
    ``obs/slo.py`` by path; this rule generalizes it to the actual
    reachable set: starting from every function in ``sim/scenario.py``
    it follows the graph into ``parallel/``, ``obs/``, ``utils/`` —
    wherever the scenarios really go — and flags direct
    ``time.monotonic()``-family reads and ``loop.time()`` there.
    Deliberate wall-clock sites (profiling of real thread work, which
    the virtual loop gives zero width by design) record why with
    ``# lint: clock-escape-ok <reason>``.
    """

    id = "CB302"
    slug = "clock-escape"
    description = ("sim-scenario-reachable code must read time through "
                   "the clock seam")
    project = True

    SCENARIO_ROOTS = (("sim/scenario.py", "*"),)

    def check(self, sf) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError("project rule: use check_project")

    @staticmethod
    def _alias_tables(tree: ast.AST) -> tuple[set, dict]:
        """(time-module aliases, bare-name -> spelled time fn) — the
        CB108 alias convention, computed once per module."""
        module_aliases = {"time"}
        func_aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        module_aliases.add(alias.asname or "time")
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == "time":
                for alias in node.names:
                    if alias.name in ClockSeamRule.DIRECT_NAMES:
                        func_aliases[alias.asname or alias.name] = \
                            f"time.{alias.name}"
        return module_aliases, func_aliases

    def check_project(self, sfs, ctx) -> Iterator[tuple]:
        roots = ctx.resolve_roots(self.SCENARIO_ROOTS)
        if not roots:
            return
        tables: dict[str, tuple[set, dict]] = {}
        for info in ctx.reachable_infos(roots):
            rel = info.rel
            if rel.startswith(_CLOCK_GOVERNED):
                continue
            sf = ctx.by_rel.get(rel)
            if sf is None:
                continue
            if rel not in tables:
                tables[rel] = self._alias_tables(sf.tree)
            module_aliases, func_aliases = tables[rel]
            for node in iter_body_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                spelled = None
                if isinstance(node.func, ast.Name):
                    spelled = func_aliases.get(node.func.id)
                elif isinstance(node.func, ast.Attribute):
                    chain = attr_chain(node.func)
                    base, _, attr = chain.rpartition(".")
                    if base in module_aliases \
                            and attr in ClockSeamRule.DIRECT_NAMES:
                        spelled = f"{chain}()"
                    elif (node.func.attr == "time" and not node.args
                            and chain and "loop" in chain.lower()):
                        spelled = f"{chain}() (loop.time)"
                if spelled is not None:
                    yield (rel, node.lineno, node.col_offset,
                           f"direct {spelled} in {info.qualname}() is "
                           "reachable from sim/scenario.py — inside a "
                           "virtual-time run this ticks in REAL time "
                           "and skews every derived duration; route "
                           "through the clock seam (cluster/clock.py) "
                           "or justify with `# lint: clock-escape-ok "
                           "<reason>`")


def _names_in(node: ast.AST) -> set[str]:
    """Name ids and attribute tails under ``node`` — 'what does this
    expression observe', for matching cancels to their awaits."""
    out: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


def _suspensions(stmts) -> list[ast.AST]:
    """Suspension points executing as part of ``stmts`` themselves:
    ``await`` plus the implicit suspensions of ``async for`` /
    ``async with``; nested def/lambda subtrees excluded (their awaits
    run when THEY are called)."""
    out: list[ast.AST] = []
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


class CancelSafetyRule(Rule):
    """CB303 — cancellation must propagate, complete, and never strand
    a publish.

    Async teardown in this codebase is load-bearing: sim.run() and the
    SANITIZE=1 tier-1 leg both require every task to finish when
    cancelled (CLAUDE.md: 0 leaked tasks).  Three shapes break that:

    (a) *swallowed cancellation* — ``except CancelledError:`` /
        ``except BaseException:`` / bare ``except:`` around awaits with
        no re-raise absorbs the coroutine's OWN cancellation; teardown
        then waits forever.  The sanctioned child-reap (``child.
        cancel()`` before the try, awaiting that child inside it)
        passes — there the CancelledError belongs to the child.
    (b) *cancel without await* — ``task.cancel()`` only REQUESTS
        cancellation; until the task is awaited (or gathered) it may
        still be mid-finally holding locks and file handles.  Every
        cancel of a task variable needs a later await/gather that
        observes it (directly or through the collection it came from).
    (c) *unshielded await inside a publish window* — between a
        finished write and its ``replace`` an arriving cancellation
        strands the temp file and skips the publish; wrap the window
        in ``asyncio.shield`` or keep it await-free (the
        ``_publish_atomically`` shape).

    Justified sites record why with ``# lint: cancel-safety-ok
    <reason>``.
    """

    id = "CB303"
    slug = "cancel-safety"
    description = ("cancellation must be re-raised, awaited after "
                   "cancel(), and kept out of publish windows")

    #: receivers whose .cancel() needs no await: loop TimerHandles and
    #: timers complete synchronously
    _HANDLE_HINTS = ("handle", "timer")

    def applies(self, rel: str) -> bool:
        return not rel.startswith("analysis/")

    def check(self, sf) -> Iterator[Finding]:
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            yield from self._check_swallowed(fn)
            yield from self._check_cancel_without_await(fn)
            yield from self._check_publish_window(fn)

    # -- (a) swallowed CancelledError --

    @staticmethod
    def _catches_cancelled(type_node) -> bool:
        if type_node is None:
            return True  # bare except
        if isinstance(type_node, ast.Tuple):
            return any(CancelSafetyRule._catches_cancelled(el)
                       for el in type_node.elts)
        chain = attr_chain(type_node)
        tail = chain.rsplit(".", 1)[-1]
        return tail in ("CancelledError", "BaseException")

    def _check_swallowed(self, fn) -> Iterator[Finding]:
        body_nodes = list(iter_body_nodes(fn))
        cancels: list[tuple[int, set[str]]] = []
        for node in body_nodes:
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "cancel"):
                cancels.append((node.lineno, _names_in(node.func.value)))
        for node in body_nodes:
            if not isinstance(node, ast.Try):
                continue
            try_susp = _suspensions(node.body)
            if not try_susp:
                continue  # nothing to interrupt: nothing swallowed
            observed = set()
            for s in try_susp:
                observed |= _names_in(s)
            for handler in node.handlers:
                if not self._catches_cancelled(handler.type):
                    continue
                if any(isinstance(n, ast.Raise)
                       for n in ast.walk(handler)):
                    continue  # re-raises on some path
                cancelled_before = set()
                for line, names in cancels:
                    if line <= handler.lineno:
                        cancelled_before |= names
                if cancelled_before & observed:
                    # the child-reap idiom: the await observes a task
                    # this function cancelled — the CancelledError
                    # being swallowed is the child's, not ours
                    continue
                shown = "bare except" if handler.type is None else \
                    f"except {ast.unparse(handler.type)}"
                yield (handler.lineno, handler.col_offset,
                       f"{shown} around awaits in async def "
                       f"{fn.name}() swallows CancelledError — the "
                       "coroutine absorbs its own cancellation and "
                       "teardown hangs (sim.run / SANITIZE leg); "
                       "re-raise it, or justify with "
                       "`# lint: cancel-safety-ok <reason>`")

    # -- (b) cancel() never awaited --

    def _check_cancel_without_await(self, fn) -> Iterator[Finding]:
        parents = _parents(fn)
        susp = [(s.lineno, _names_in(s))
                for s in _suspensions(fn.body)]
        for node in iter_body_nodes(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "cancel"
                    and isinstance(node.func.value, ast.Name)):
                continue
            recv = node.func.value.id
            if any(h in recv.lower() for h in self._HANDLE_HINTS):
                continue  # TimerHandle.cancel() completes synchronously
            watch = {recv}
            # a cancel inside `for t in tasks:` (or `for t, meta in
            # d.items():`) is observed by awaiting the collection
            # (`gather(*tasks)`) just as well as t
            cur = parents.get(node)
            while cur is not None and cur is not fn:
                if isinstance(cur, (ast.For, ast.AsyncFor)):
                    target_names = {
                        n.id for n in ast.walk(cur.target)
                        if isinstance(n, ast.Name)}
                    if recv in target_names:
                        watch |= _names_in(cur.iter)
                cur = parents.get(cur)
            if any(line >= node.lineno and (names & watch)
                   for line, names in susp):
                continue
            yield (node.lineno, node.col_offset,
                   f"{recv}.cancel() in async def {fn.name}() is never "
                   "awaited afterwards — cancellation is only "
                   "requested, the task may still be running (holding "
                   "locks/files) when this coroutine moves on; await "
                   "it (gather(..., return_exceptions=True)) or "
                   "justify with `# lint: cancel-safety-ok <reason>`")

    # -- (c) awaits inside the write->replace publish window --

    def _check_publish_window(self, fn) -> Iterator[Finding]:
        susp = _suspensions(fn.body)
        write_awaits = []
        for s in susp:
            if not isinstance(s, ast.Await) \
                    or not isinstance(s.value, ast.Call):
                continue
            tail = attr_chain(s.value.func).rsplit(".", 1)[-1]
            if "write" in tail or tail in ("flush", "fsync"):
                write_awaits.append(s)
        if not write_awaits:
            return
        replaces = [
            node for node in iter_body_nodes(fn)
            if isinstance(node, ast.Call)
            and attr_chain(node.func).rsplit(".", 1)[-1] == "replace"]
        flagged: set[int] = set()
        for rep in replaces:
            befores = [w.lineno for w in write_awaits
                       if w.lineno < rep.lineno]
            if not befores:
                continue
            window_start = max(befores)
            for s in susp:
                if s in write_awaits or s.lineno in flagged:
                    continue
                if not (window_start < s.lineno <= rep.lineno):
                    continue
                if isinstance(s, ast.Await) \
                        and isinstance(s.value, ast.Call) \
                        and attr_chain(s.value.func).rsplit(
                            ".", 1)[-1] == "shield":
                    continue
                flagged.add(s.lineno)
                yield (s.lineno, s.col_offset,
                       f"await between a finished write and replace() "
                       f"in async def {fn.name}(): a cancellation "
                       "delivered here strands the temp file and "
                       "skips the publish — keep the window "
                       "await-free (the _publish_atomically shape) or "
                       "wrap it in asyncio.shield, else justify with "
                       "`# lint: cancel-safety-ok <reason>`")


class SimPurityRule(Rule):
    """CB304 — production planes import nothing from ``sim/``.

    The seam points one way (CLAUDE.md sim plane: "Production paths
    import NOTHING from sim/"): the simulator wraps production
    machinery, never the reverse — a production module that reaches
    into ``sim/`` would couple serving behavior to the test double and
    quietly change what ships.  tests/test_sim.py's subprocess pin
    proves the property at runtime for the default import closure;
    this rule proves it statically for every module INCLUDING lazy
    in-function imports, which the runtime pin only sees on the code
    paths it happens to execute.  The one sanctioned inversion — the
    ``sim:`` Location kind resolving its fabric lazily — records why
    inline with ``# lint: sim-purity-ok <reason>``.
    """

    id = "CB304"
    slug = "sim-purity"
    description = ("production modules must not import chunky_bits_tpu"
                   ".sim (the seam points one way)")

    def applies(self, rel: str) -> bool:
        return not rel.startswith(("sim/", "analysis/"))

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            hit = ""
            if isinstance(node, ast.Import):
                for alias in node.names:
                    parts = alias.name.split(".")
                    if "sim" in parts:
                        hit = f"import {alias.name}"
                        break
            elif isinstance(node, ast.ImportFrom):
                mod_parts = (node.module or "").split(".")
                if "sim" in mod_parts:
                    hit = f"from {'.' * node.level}{node.module} import"
                elif any(a.name == "sim" for a in node.names):
                    hit = (f"from {'.' * node.level}"
                           f"{node.module or ''} import sim")
            if hit:
                yield (node.lineno, node.col_offset,
                       f"{hit}: production code importing the "
                       "simulator inverts the sim seam — sim/ wraps "
                       "production machinery, never the reverse; "
                       "invert the dependency or justify with "
                       "`# lint: sim-purity-ok <reason>`")


class LabelFlowRule(Rule):
    """CB305 — closed-set label discipline, one call hop deep.

    CB107 lets a plain parameter name through ``.labels()`` because the
    closed set may be enforced upstream — which makes the *call sites*
    the place the discipline actually holds or breaks.  This rule finds
    functions that feed a parameter into ``.labels()`` and applies
    CB107's open-endedness test (f-string / string-building / call
    result / request-derived chain) to the argument each recorded call
    site passes for that parameter.  Findings land at the call site —
    that is where the open-ended value enters the metrics plane — and
    clamp-at-the-caller is the fix, same as CB107; a provably-closed
    dynamic value records why with ``# lint: label-flow-ok <reason>``.
    """

    id = "CB305"
    slug = "label-flow"
    description = ("arguments feeding metric label parameters must be "
                   "closed-set at every call site")
    project = True

    def check(self, sf) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError("project rule: use check_project")

    def check_project(self, sfs, ctx) -> Iterator[tuple]:
        graph = ctx.graph
        judge = MetricLabelCardinalityRule()
        seen: set[tuple] = set()
        for key, info in sorted(graph.functions.items()):
            if info.rel.startswith("analysis/") \
                    or isinstance(info.node, ast.Lambda):
                continue
            args = info.node.args
            pos_params = [a.arg for a in (list(args.posonlyargs)
                                          + list(args.args))]
            all_params = set(pos_params) | {
                a.arg for a in args.kwonlyargs}
            label_params: list[str] = []
            for node in iter_body_nodes(info.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "labels"):
                    for val in list(node.args) + [kw.value for kw
                                                  in node.keywords]:
                        if isinstance(val, ast.Name) \
                                and val.id in all_params:
                            label_params.append(val.id)
            if not label_params:
                continue
            bound_offset = 1 if (info.cls is not None and pos_params
                                 and pos_params[0] in ("self", "cls")) \
                else 0
            for caller_key, call in graph.call_sites.get(key, ()):
                for pname in label_params:
                    arg_node = None
                    if pname in pos_params:
                        idx = pos_params.index(pname)
                        cidx = idx - bound_offset \
                            if isinstance(call.func, ast.Attribute) \
                            else idx
                        if 0 <= cidx < len(call.args):
                            arg_node = call.args[cidx]
                    for kw in call.keywords:
                        if kw.arg == pname:
                            arg_node = kw.value
                    if arg_node is None:
                        continue
                    why = judge._open_ended(arg_node)
                    if not why:
                        continue
                    mark = (caller_key[0], arg_node.lineno,
                            arg_node.col_offset, pname)
                    if mark in seen:
                        continue
                    seen.add(mark)
                    yield (caller_key[0], arg_node.lineno,
                           arg_node.col_offset,
                           f"argument for metric label parameter "
                           f"'{pname}' of {info.qualname}() is {why}: "
                           "one hop later it becomes a label value — "
                           "clamp to a closed set at this call site, "
                           "or justify with `# lint: label-flow-ok "
                           "<reason>`")


FLOW_RULES: tuple[Rule, ...] = (
    FsioEscapeRule(),
    ClockEscapeRule(),
    CancelSafetyRule(),
    SimPurityRule(),
    LabelFlowRule(),
)
