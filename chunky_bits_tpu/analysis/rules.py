"""The project-native lint rules.

Each rule pins one CLAUDE.md invariant to AST shape.  They are
heuristics with an escape hatch by design: an inline
``# lint: <slug>-ok <reason>`` records WHY a flagged site is safe, so
the justification lives next to the code it excuses and shows up in
review when either changes.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

#: path prefixes of the device/network call paths — the routes where an
#: unbounded wait or a non-daemon worker can hang a serve or block exit
#: (file_part.py, destination.py and health.py joined with the hedged
#: I/O scheduler: every await the read race / write failover adds must
#: stay reachable through a timeout; slab.py, scrub.py and repair.py
#: joined with the packed store + scrub daemon + repair planner: a
#: long-running background walker is exactly the shape that hangs a
#: shutdown if any wait is unbounded)
#: obs/ rides along: the metrics/tracing plane is called from every
#: serve path, so a blocking or unbounded wait there stalls the same
#: loops the rest of this list protects
DEVICE_NET_PATHS = ("ops/", "parallel/", "gateway/", "obs/",
                    "file/chunk_cache.py",
                    "file/file_part.py", "file/slab.py",
                    "cluster/destination.py", "cluster/health.py",
                    "cluster/scrub.py", "cluster/repair.py",
                    "cluster/meta_log.py", "cluster/qos.py")

ENV_PREFIX = "CHUNKY_BITS_TPU_"

#: the one module allowed to read CHUNKY_BITS_TPU_* from the process
#: environment; everything else goes through its accessors
ENV_HOME = "cluster/tunables.py"

#: the strict-typing public surfaces (mirrors the [tool.mypy] overrides
#: in pyproject.toml, which enforce the same set when mypy is installed)
STRICT_TYPED_MODULES = (
    "ops/backend.py",
    "file/chunk_cache.py",
    "cluster/tunables.py",
    "file/file_part.py",
    "parallel/backend.py",
)

Finding = tuple[int, int, str]


def rule_family(rule_id: str) -> str:
    """Family id for a rule id ('CB101' -> 'CB1xx') — the ONE place the
    derivation lives; ``Rule.family`` and the CLI's --json
    ``rule_family`` field both come through here."""
    return f"{rule_id[:3]}xx"


class Rule:
    id: str = ""
    slug: str = ""
    description: str = ""
    #: rel-path prefixes the rule applies to; () = every file
    paths: tuple[str, ...] = ()
    #: project rules see every parsed file at once via
    #: ``check_project(sfs)`` (interprocedural passes — see core.py)
    project: bool = False

    @property
    def family(self) -> str:
        """Rule family id, 'CB1xx' / 'CB2xx' (the --select prefix and
        the --json ``rule_family`` field)."""
        return rule_family(self.id)

    def applies(self, rel: str) -> bool:
        if not self.paths:
            return True
        return any(rel == p or rel.startswith(p) for p in self.paths)

    def check(self, sf) -> Iterator[Finding]:
        raise NotImplementedError


def _attr_chain(node: ast.AST) -> str:
    """Dotted name for Name/Attribute chains ('os.environ.get'), or ''."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _parents(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    par: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


class UnboundedAwaitRule(Rule):
    """CB101 — degrade, never hang (CLAUDE.md).

    On device/network call paths every wait must be bounded: PJRT park
    or a dead peer otherwise hangs the serve forever.  Flags ``await``
    on bare futures/tasks and on the known-unbounded wait primitives
    (``.wait()``, ``.wait_closed()``, ``.join()``, ``.serve_forever()``,
    ``run_in_executor``).  Bounded alternatives: ``asyncio.wait_for``,
    the dispatch-timeout wrappers (ops/jax_backend.run_bounded_dispatch),
    or a liveness argument recorded via
    ``# lint: unbounded-await-ok <reason>``.
    """

    id = "CB101"
    slug = "unbounded-await"
    description = ("await on device/network paths must be reachable "
                   "through a timeout guard")
    paths = DEVICE_NET_PATHS

    WATCH = ("wait", "wait_closed", "join", "serve_forever",
             "run_in_executor")

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Await):
                continue
            value = node.value
            if isinstance(value, (ast.Name, ast.Attribute)):
                yield (node.lineno, node.col_offset,
                       "await on a bare future/task is unbounded; wrap "
                       "in asyncio.wait_for or justify with "
                       "`# lint: unbounded-await-ok <reason>`")
            elif (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr in self.WATCH):
                yield (node.lineno, node.col_offset,
                       f"await .{value.func.attr}() has no deadline; "
                       "wrap in asyncio.wait_for or justify with "
                       "`# lint: unbounded-await-ok <reason>`")


class EnvFlagDisciplineRule(Rule):
    """CB102 — flags are read at first dispatch and baked into jit
    caches (CLAUDE.md), so scattered ad-hoc reads make 'where is this
    knob read, and when' unanswerable.  All ``CHUNKY_BITS_TPU_*``
    environment reads go through cluster/tunables.py accessors
    (``env_flag`` / ``env_seconds`` / ``env_str``); a deliberate
    first-dispatch read elsewhere carries
    ``# lint: env-read-ok <reason>``.  Writes (the CLI's backend
    handoff) are out of scope — the hazard is read placement.
    """

    id = "CB102"
    slug = "env-read"
    description = ("CHUNKY_BITS_TPU_* environment reads belong in "
                   "cluster/tunables.py accessors")

    def applies(self, rel: str) -> bool:
        return rel != ENV_HOME and not rel.startswith("analysis/")

    def _key_of(self, sf, node: ast.AST) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return sf.constants.get(node.id, "")
        return ""

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            key = ""
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain in ("os.environ.get", "environ.get",
                             "os.environ.setdefault",
                             "environ.setdefault",
                             "os.getenv", "getenv") and node.args:
                    key = self._key_of(sf, node.args[0])
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and _attr_chain(node.value) in ("os.environ",
                                                    "environ")):
                key = self._key_of(sf, node.slice)
            if key.startswith(ENV_PREFIX):
                yield (node.lineno, node.col_offset,
                       f"direct read of ${key}: route through "
                       "cluster/tunables.py accessors (env_flag/"
                       "env_seconds/env_str) or justify a designated "
                       "first-dispatch site with "
                       "`# lint: env-read-ok <reason>`")


class NonDaemonThreadRule(Rule):
    """CB103 — 1-core box: ThreadPoolExecutor workers are non-daemon
    and join at interpreter exit, so one worker parked inside PJRT
    blocks exit forever (CLAUDE.md).  Device-wait paths use plain
    ``threading.Thread(daemon=True)``; a pool that provably never
    touches the device records that with ``# lint: thread-ok <reason>``.
    """

    id = "CB103"
    slug = "thread"
    description = ("no ThreadPoolExecutor / non-daemon Thread on "
                   "device-wait paths")
    paths = ("ops/", "parallel/")

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1]
            if tail == "ThreadPoolExecutor":
                yield (node.lineno, node.col_offset,
                       "ThreadPoolExecutor on a device-wait path: its "
                       "non-daemon workers block interpreter exit when "
                       "parked in PJRT — use threading.Thread("
                       "daemon=True) or justify with "
                       "`# lint: thread-ok <reason>`")
            elif tail == "Thread" and chain in ("Thread",
                                                "threading.Thread"):
                daemon_true = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords)
                if not daemon_true:
                    yield (node.lineno, node.col_offset,
                           "non-daemon Thread on a device-wait path "
                           "blocks interpreter exit when parked in "
                           "PJRT; pass daemon=True or justify with "
                           "`# lint: thread-ok <reason>`")


class BroadExceptRule(Rule):
    """CB104 — degraded-mode fallbacks must not silently eat corruption
    signals.  ``except Exception`` (or broader) is allowed only when it
    (a) ends in a ``raise`` (nothing can be swallowed), or (b) carries a
    ``# lint: broad-except-ok <reason>`` justification — so every
    swallow-and-continue site states what it degrades to and why that
    cannot hide corruption.  ``# noqa: BLE001 <reason>`` is accepted as
    the same marker.
    """

    id = "CB104"
    slug = "broad-except"
    description = ("broad except handlers must re-raise or carry a "
                   "justification")

    BROAD = ("Exception", "BaseException")

    def _is_broad(self, type_node) -> bool:
        if type_node is None:
            return True  # bare except:
        if isinstance(type_node, ast.Name):
            return type_node.id in self.BROAD
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(el) for el in type_node.elts)
        return False

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if node.body and isinstance(node.body[-1], ast.Raise):
                continue  # terminal re-raise: cannot swallow
            shown = "bare except" if node.type is None else \
                f"except {ast.unparse(node.type)}"
            yield (node.lineno, node.col_offset,
                   f"{shown} without a terminal raise can swallow "
                   "corruption signals; narrow the type or justify "
                   "with `# lint: broad-except-ok <reason>`")


class JitBodyHygieneRule(Rule):
    """CB105 — this jax build's XLA CPU backend mishandles two jit-body
    shapes (CLAUDE.md, ops/sha256_jax.py docstrings): unrolled ~2000-op
    integer bodies blow up compile superlinearly (use ``fori_loop``),
    and odd-width u8 device concats can spin forever at runtime (keep
    device buffers 64-aligned).  Flags large-literal ``range`` loops
    inside traced functions, and ``jnp.concatenate``/``stack`` calls —
    the latter must record their alignment argument via
    ``# lint: jit-hygiene-ok <why aligned>`` or live in the baseline.
    """

    id = "CB105"
    slug = "jit-hygiene"
    description = ("no unrolled loop bodies or unjustified device "
                   "concats in ops/ jit code")
    paths = ("ops/",)

    UNROLL_THRESHOLD = 64
    CONCAT = ("concatenate", "stack", "hstack", "vstack")
    TRACE_NAMES = ("jnp", "lax", "pl", "plgpu", "pltpu")

    def check(self, sf) -> Iterator[Finding]:
        parents = _parents(sf.tree)

        def nearest_def(node: ast.AST):
            cur = parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cur = parents.get(cur)
            return cur

        traced_cache: dict[ast.AST, bool] = {}

        def is_traced(fn) -> bool:
            if fn is None:
                return False
            if fn not in traced_cache:
                traced_cache[fn] = any(
                    isinstance(n, ast.Name) and n.id in self.TRACE_NAMES
                    for n in ast.walk(fn))
            return traced_cache[fn]

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.For):
                it = node.iter
                if not (isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id == "range"):
                    continue
                bound = max((a.value for a in it.args
                             if isinstance(a, ast.Constant)
                             and isinstance(a.value, int)), default=0)
                if bound >= self.UNROLL_THRESHOLD \
                        and is_traced(nearest_def(node)):
                    yield (node.lineno, node.col_offset,
                           f"range({bound}) loop in a traced function "
                           "unrolls into the jit body (superlinear "
                           "compile blow-up on this XLA CPU backend); "
                           "use jax.lax.fori_loop, or justify with "
                           "`# lint: jit-hygiene-ok <reason>`")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.CONCAT
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "jnp"):
                yield (node.lineno, node.col_offset,
                       f"jnp.{node.func.attr} in ops/: odd-width u8 "
                       "device concats can spin forever on this XLA "
                       "CPU backend — state the lane-alignment "
                       "argument with `# lint: jit-hygiene-ok <why "
                       "aligned>` (see ops/sha256_jax.py docstrings)")


class PublicAnnotationsRule(Rule):
    """CB106 — the runnable half of the strict typing gate: the public
    surfaces listed in ``STRICT_TYPED_MODULES`` must carry full
    parameter and return annotations.  mypy (when installed — see
    scripts/check.sh) enforces consistency; this rule enforces presence
    even on boxes without mypy, so the tier-1 gate never silently loses
    the typing floor.
    """

    id = "CB106"
    slug = "annotations"
    description = ("public functions on strict-typed modules need full "
                   "annotations")
    paths = STRICT_TYPED_MODULES

    def applies(self, rel: str) -> bool:
        return rel in self.paths

    def check(self, sf) -> Iterator[Finding]:
        def check_fn(fn, is_method: bool) -> Iterator[Finding]:
            if fn.name.startswith("_"):
                return
            args = fn.args
            named = (list(args.posonlyargs) + list(args.args)
                     + list(args.kwonlyargs))
            if is_method and named and named[0].arg in ("self", "cls"):
                named = named[1:]
            missing = [a.arg for a in named if a.annotation is None]
            for extra in (args.vararg, args.kwarg):
                if extra is not None and extra.annotation is None:
                    missing.append(f"*{extra.arg}")
            if missing:
                yield (fn.lineno, fn.col_offset,
                       f"public {'method' if is_method else 'function'} "
                       f"{fn.name}() missing parameter annotations: "
                       f"{', '.join(missing)} (strict typing gate)")
            if fn.returns is None:
                yield (fn.lineno, fn.col_offset,
                       f"public {'method' if is_method else 'function'} "
                       f"{fn.name}() missing a return annotation "
                       "(strict typing gate)")

        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from check_fn(node, is_method=False)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        is_static = any(
                            isinstance(d, ast.Name)
                            and d.id == "staticmethod"
                            for d in sub.decorator_list)
                        yield from check_fn(sub,
                                            is_method=not is_static)


class MetricLabelCardinalityRule(Rule):
    """CB107 — metric label values must come from closed sets
    (obs/metrics.py's cardinality rule): a label minted from a request
    path, a client header, or any other open-ended string grows one
    time series per distinct value — an unbounded memory leak and a
    scrape bomb.  Flags ``.labels(...)`` arguments that are f-strings,
    string-building expressions, call results, or request-derived
    attribute chains; plain literals and names (bound upstream to
    clamped/closed values) pass.  The registry's MAX_LABEL_SETS ceiling
    is the runtime backstop; a provably-closed dynamic value records
    its argument with ``# lint: label-cardinality-ok <reason>``.
    """

    id = "CB107"
    slug = "label-cardinality"
    description = ("metric label values must come from closed sets, "
                   "never request-derived strings")

    #: attribute chains that scream "request-derived"
    TAINTED = ("request.", "req.")
    TAINTED_ATTRS = ("path", "query_string", "rel_url", "match_info",
                     "headers")

    def _open_ended(self, node: ast.AST) -> str:
        if isinstance(node, ast.JoinedStr):
            return "an f-string"
        if isinstance(node, ast.BinOp):
            return "a string-building expression"
        if isinstance(node, ast.Call):
            return "a call result"
        chain = _attr_chain(node)
        if chain:
            if any(chain.startswith(t) for t in self.TAINTED):
                return f"request-derived ({chain})"
            tail = chain.rsplit(".", 1)[-1]
            if "." in chain and tail in self.TAINTED_ATTRS:
                return f"request-derived ({chain})"
        return ""

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"):
                continue
            values = list(node.args) + [kw.value for kw in node.keywords]
            for val in values:
                why = self._open_ended(val)
                if why:
                    yield (val.lineno, val.col_offset,
                           f"metric label value is {why}: label values "
                           "must come from a closed set (clamp first, "
                           "like obs/metrics.record_request), or "
                           "justify with `# lint: label-cardinality-ok "
                           "<reason>`")


class ClockSeamRule(Rule):
    """CB108 — the clock seam cannot silently rot.

    Every time-sensitive policy in the cluster/file planes (EWMA decay,
    breaker cooldowns, token buckets, hedge delays, retry backoff, I/O
    latency samples) resolves time through ``cluster/clock.py`` (the
    seam ``chunky_bits_tpu/utils/clock.py`` implements), so the
    deterministic cluster simulator (``chunky_bits_tpu/sim``) can swap
    in a virtual clock and compress hours of scenario into seconds.  A
    direct ``time.monotonic()`` / ``time.time()`` / ``loop.time()``
    read in ``cluster/``, ``file/``, ``ops/batching.py`` or
    ``obs/slo.py`` (the SLO engine's window arithmetic MUST compress
    with the scenario it observes, or detection latency would be
    measured on the wrong timebase) would tick in REAL time inside a
    virtual-time run — every duration touching it silently corrupts.
    Justified wall-clock sites (human-facing timestamps like slab
    publish stamps) carry ``# lint: clock-ok <reason>``; the seam
    module itself is the one sanctioned home for direct reads.
    """

    id = "CB108"
    slug = "clock"
    description = ("cluster/file-plane time reads go through the "
                   "cluster/clock.py seam")
    paths = ("cluster/", "file/", "ops/batching.py", "obs/slo.py")

    #: the clock-read function names (incl. the nanosecond spellings —
    #: a ns read mixes timebases just as silently); alias-import
    #: tracking follows the CB102 convention: `from time import
    #: monotonic` and `import time as t` must not slip past the lint
    DIRECT_NAMES = ("monotonic", "time", "perf_counter",
                    "monotonic_ns", "time_ns", "perf_counter_ns")

    #: direct stdlib reads that bypass the seam outright
    DIRECT = tuple(f"time.{name}" for name in DIRECT_NAMES)

    def applies(self, rel: str) -> bool:
        return rel != "cluster/clock.py" and super().applies(rel)

    @staticmethod
    def _is_loop_call(value: ast.AST) -> bool:
        """True when ``value`` is a call that yields an event loop
        (``asyncio.get_running_loop()`` / ``get_event_loop()`` /
        ``new_event_loop()``) — the call-result spelling of
        ``loop.time()``."""
        if not isinstance(value, ast.Call):
            return False
        callee = _attr_chain(value.func)
        return callee.rsplit(".", 1)[-1] in (
            "get_running_loop", "get_event_loop", "new_event_loop")

    def check(self, sf) -> Iterator[Finding]:
        # alias imports first, so renamed spellings can't slip past:
        # `import time as t` -> t.monotonic(); `from time import
        # monotonic [as m]` -> bare monotonic()/m()
        module_aliases = {"time"}
        func_aliases: dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        module_aliases.add(alias.asname or "time")
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == "time":
                for alias in node.names:
                    if alias.name in self.DIRECT_NAMES:
                        func_aliases[alias.asname or alias.name] = \
                            f"time.{alias.name}"
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                spelled = func_aliases.get(node.func.id)
                if spelled is not None:
                    yield (node.lineno, node.col_offset,
                           f"direct {spelled}() (imported as "
                           f"{node.func.id}) bypasses the clock seam "
                           "— route through clock.monotonic() or "
                           "justify with `# lint: clock-ok <reason>`")
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            chain = _attr_chain(node.func)
            base, _, attr = chain.rpartition(".")
            if chain in self.DIRECT or (
                    base in module_aliases
                    and attr in self.DIRECT_NAMES):
                yield (node.lineno, node.col_offset,
                       f"direct {chain}() bypasses the clock seam "
                       "(cluster/clock.py; file/ modules import "
                       "chunky_bits_tpu.utils.clock) — a virtual-time "
                       "run would silently mix timebases; route "
                       "through clock.monotonic() or justify with "
                       "`# lint: clock-ok <reason>`")
            elif (node.func.attr == "time" and not node.args
                    and chain != "time.time"
                    and ("loop" in chain.lower() if chain
                         else self._is_loop_call(node.func.value))):
                # loop.time() in any spelling: a named loop variable
                # or a get_running_loop()/get_event_loop() call result
                # (an arbitrary call result — datetime.now().time() —
                # is NOT a loop and must not force a bogus suppression)
                yield (node.lineno, node.col_offset,
                       "loop.time() bypasses the clock seam — on the "
                       "simulator's loop it happens to be virtual, but "
                       "production durations must come off ONE clock "
                       "(clock.monotonic()); justify deliberate sites "
                       "with `# lint: clock-ok <reason>`")


class FsioSeamRule(Rule):
    """CB109 — the filesystem seam cannot silently rot.

    Every durability-relevant op on the storage plane — slab append +
    journal commit, compaction swap, atomic chunk/metadata
    publication, the repair planner's in-place rewrites — resolves
    through ``file/fsio.py`` (the seam ``chunky_bits_tpu/utils/fsio.py``
    implements), so the crash-consistency harness
    (``chunky_bits_tpu/sim/crash.py``) can record the exact op stream
    of a mutation and replay every "crash at op k" prefix into a
    cloned directory.  A direct ``os.replace``/``os.fsync``/
    ``os.unlink``/write-mode ``open`` (and friends) in the storage
    modules would mutate disk state INVISIBLY to the recorder — the
    crash matrix would go green while skipping the very op that tears.
    Deliberate off-seam sites (read-side probes, lock files) carry
    ``# lint: fsio-ok <reason>``; the seam modules themselves are the
    sanctioned homes for direct calls.
    """

    id = "CB109"
    slug = "fsio"
    description = ("storage-plane durability ops go through the "
                   "file/fsio.py seam")
    paths = ("file/slab.py", "file/location.py", "cluster/metadata.py",
             "cluster/meta_log.py", "cluster/repair.py",
             "cluster/scrub.py")

    #: the os-level durability verbs the seam wraps (os.rename rides
    #: along: it is os.replace minus the overwrite guarantee)
    OS_VERBS = ("replace", "rename", "fsync", "unlink", "remove",
                "truncate", "ftruncate", "makedirs", "mkdir", "rmdir",
                "open", "write")

    def _mode_of(self, node: ast.Call) -> str:
        """The literal mode argument of a builtin-open call, or ''."""
        mode_node: Optional[ast.AST] = None
        if len(node.args) >= 2:
            mode_node = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
        if isinstance(mode_node, ast.Constant) \
                and isinstance(mode_node.value, str):
            return mode_node.value
        return ""

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain.startswith("os."):
                verb = chain[3:].split(".", 1)[0]
                if verb in self.OS_VERBS:
                    yield (node.lineno, node.col_offset,
                           f"direct {chain}() bypasses the filesystem "
                           "seam — the crash harness cannot record or "
                           "replay this mutation; route through "
                           "file/fsio.py (fsio.replace/fsio.fsync/"
                           "fsio.open/...) or justify with "
                           "`# lint: fsio-ok <reason>`")
            elif chain == "open":
                mode = self._mode_of(node)
                if any(c in mode for c in "wax+"):
                    yield (node.lineno, node.col_offset,
                           f"write-mode open({mode!r}) bypasses the "
                           "filesystem seam — the crash harness cannot "
                           "record or replay this mutation; use "
                           "fsio.open or justify with "
                           "`# lint: fsio-ok <reason>`")


#: one-line hazard descriptions for --list-rules family grouping
FAMILY_HAZARDS = {
    "CB1xx": ("single-function invariants: bounded waits, env-flag "
              "discipline, daemon threads, narrow excepts, jit "
              "hygiene, typing floor, metric label cardinality, "
              "clock-seam discipline, filesystem-seam discipline"),
    "CB2xx": ("concurrency hazards of the two-plane host/async "
              "runtime: blocked loops, cross-plane handoffs, leaked "
              "tasks, loop-spanning shared state"),
    "CB3xx": ("whole-program reachability: seam escapes beyond the "
              "CB108/CB109 path lists, cancellation safety, sim-plane "
              "purity, label flow across call sites — all over the "
              "function-granular call graph (analysis/callgraph.py)"),
    "CB4xx": ("resource lifetime & deadline propagation over "
              "statement-granular CFGs with exception/finally/"
              "cancellation edges (analysis/cfg.py) and gen/kill "
              "dataflow, summaries composed through the call graph: "
              "handles closed on every path, locks always released, "
              "tasks always owned, awaits bounded at some frame, "
              "scrub/repair I/O charged before it happens"),
}

# imported at the bottom: concurrency.py, flow.py and lifetime.py need
# Rule defined first
from chunky_bits_tpu.analysis.concurrency import (  # noqa: E402
    CONCURRENCY_RULES,
)
from chunky_bits_tpu.analysis.flow import FLOW_RULES  # noqa: E402
from chunky_bits_tpu.analysis.lifetime import (  # noqa: E402
    LIFETIME_RULES,
)

ALL_RULES: tuple[Rule, ...] = (
    UnboundedAwaitRule(),
    EnvFlagDisciplineRule(),
    NonDaemonThreadRule(),
    BroadExceptRule(),
    JitBodyHygieneRule(),
    PublicAnnotationsRule(),
    MetricLabelCardinalityRule(),
    ClockSeamRule(),
    FsioSeamRule(),
) + CONCURRENCY_RULES + FLOW_RULES + LIFETIME_RULES
