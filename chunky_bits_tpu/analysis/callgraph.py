"""Module-granular call graph for the CB2xx concurrency rules.

The CB204 cross-plane rule needs an answer to "can this function run on
a HostPipeline worker thread?" — a *reachability* question, so this
module builds the first interprocedural pass in ``analysis/``.  It is
deliberately module-granular and name-based (pure stdlib ``ast``, no
imports resolved, no types inferred):

* **Nodes** are every ``def`` / ``async def`` / ``lambda`` in the
  scanned files, keyed ``(rel, qualname)`` where qualname is the dotted
  class/function nesting path (lambdas get ``<lambda>@line:col``).
* **Edges** resolve by name within one module: ``f(...)`` links to any
  same-module function whose last qualname segment is ``f``;
  ``self.m(...)`` / ``cls.m(...)`` links to any same-module *method*
  named ``m`` (override-coarse on purpose: a base-class dispatch must
  reach every same-named override the module defines).
* **Roots** are the places code hops OFF the event loop onto a plain
  thread: ``threading.Thread(target=...)``, ``asyncio.to_thread(f,
  ...)``, ``loop.run_in_executor(None, f, ...)``, job callables handed
  to the host pipeline (``_Job(stage, fn)``, ``.submit(stage, fn)``,
  and ``.run(stage, fn)`` with a string stage — the async entry point
  the product read/write paths use), ``add_done_callback`` callbacks
  (they run on
  whichever thread finishes the job), and ``HostPipeline._worker``
  itself.  Callables passed to ``call_soon_threadsafe`` /
  ``run_coroutine_threadsafe`` are explicitly NOT roots — that pair is
  the sanctioned way back onto the loop.

Over-approximation (same-name collisions, overrides) errs toward
flagging, which the shared ``# lint: <slug>-ok <reason>`` machinery can
excuse; under-approximation (dynamic dispatch through stored callables,
e.g. ``job.fn()``) is exactly why the roots include every callable the
tree hands to a worker at the submit site.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

#: the sanctioned loop re-entry points: callables passed to these are
#: back ON the loop, so they are never worker roots
THREADSAFE_WRAPPERS = ("call_soon_threadsafe", "run_coroutine_threadsafe")

#: method names that are always worker bodies regardless of how they
#: are reached (the scheduler's own run loop)
ALWAYS_ROOT_METHODS = ("_worker",)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def attr_chain(node: ast.AST) -> str:
    """Dotted name for Name/Attribute chains ('loop.call_soon'), or ''."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclass
class FuncInfo:
    """One function/method/lambda node in the graph."""

    rel: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    cls: Optional[str]  # lexically enclosing class, if any

    @property
    def key(self) -> tuple[str, str]:
        return (self.rel, self.qualname)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


def iter_body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's OWN statements: descend the body but stop at
    nested def/lambda boundaries (those are separate graph nodes —
    their code runs when *they* are called, not when the outer function
    does)."""
    stack = list(ast.iter_child_nodes(fn))
    # the function's own args/defaults evaluate in the caller, skip the
    # nested bodies only
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    """Name-resolved call graph over a set of parsed files."""

    def __init__(self) -> None:
        self.functions: dict[tuple[str, str], FuncInfo] = {}
        #: key -> set of callee keys
        self.edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
        self.roots: set[tuple[str, str]] = set()
        #: per (rel, last-name-segment) function lookup for resolution
        self._by_name: dict[tuple[str, str], list[FuncInfo]] = {}

    # ---- construction ----

    def _add_function(self, info: FuncInfo) -> None:
        self.functions[info.key] = info
        self.edges.setdefault(info.key, set())
        self._by_name.setdefault((info.rel, info.name), []).append(info)

    def _collect_functions(self, rel: str, tree: ast.AST) -> dict:
        """Register every function in ``tree``; returns node -> FuncInfo
        so the edge pass can map callables back to graph nodes."""
        node_map: dict[ast.AST, FuncInfo] = {}

        def visit(node: ast.AST, quals: tuple[str, ...],
                  cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, quals + (child.name,), child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    q = ".".join(quals + (child.name,))
                    info = FuncInfo(rel, q, child, cls)
                    self._add_function(info)
                    node_map[child] = info
                    # nested defs/lambdas belong to no class: calling
                    # self.x() inside them still resolves class-wide
                    visit(child, quals + (child.name,), cls)
                elif isinstance(child, ast.Lambda):
                    q = ".".join(
                        quals + (f"<lambda>@{child.lineno}:"
                                 f"{child.col_offset}",))
                    info = FuncInfo(rel, q, child, cls)
                    self._add_function(info)
                    node_map[child] = info
                    visit(child, quals, cls)
                else:
                    visit(child, quals, cls)

        visit(tree, (), None)
        return node_map

    def _resolve_callable(self, rel: str, expr: ast.AST,
                          node_map: dict) -> list[FuncInfo]:
        """Graph nodes a callable expression may denote: a lambda is
        itself; a name/attribute resolves by last segment within the
        module (methods and functions alike)."""
        if isinstance(expr, ast.Lambda):
            info = node_map.get(expr)
            return [info] if info is not None else []
        chain = attr_chain(expr)
        if not chain:
            return []
        return list(self._by_name.get((rel, chain.rsplit(".", 1)[-1]),
                                      []))

    def _call_roots(self, rel: str, call: ast.Call,
                    node_map: dict) -> Iterator[FuncInfo]:
        """Worker-root callables referenced by one Call node."""
        func = call.func
        chain = attr_chain(func)
        tail = chain.rsplit(".", 1)[-1] if chain else ""
        candidates: list[ast.AST] = []
        if tail == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    candidates.append(kw.value)
        elif tail == "to_thread" and call.args:
            candidates.append(call.args[0])
        elif tail == "run_in_executor" and len(call.args) >= 2:
            candidates.append(call.args[1])
        elif tail == "_Job" and len(call.args) >= 2:
            candidates.append(call.args[1])
        elif (tail in ("submit", "run") and len(call.args) >= 2
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            # HostPipeline.submit(stage, fn) / await pipeline.run(stage,
            # fn) — the string stage distinguishes them from
            # concurrent.futures submit(fn, ...) and asyncio.run(coro)
            candidates.append(call.args[1])
        elif tail == "add_done_callback" and call.args:
            # completion callbacks run on whichever thread finishes the
            # job — for pipeline jobs that is a worker
            candidates.append(call.args[0])
        for expr in candidates:
            yield from self._resolve_callable(rel, expr, node_map)

    def add_module(self, rel: str, tree: ast.AST) -> None:
        node_map = self._collect_functions(rel, tree)
        # edges + roots: scan each function's own body, remembering
        # which Call nodes live inside functions so the module-level
        # pass below visits only the remainder
        in_function: set[int] = set()
        for info in [i for i in self.functions.values() if i.rel == rel]:
            for node in iter_body_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                in_function.add(id(node))
                for root in self._call_roots(rel, node, node_map):
                    self.roots.add(root.key)
                func = node.func
                if isinstance(func, ast.Name):
                    for callee in self._by_name.get(
                            (rel, func.id), []):
                        self.edges[info.key].add(callee.key)
                elif isinstance(func, ast.Attribute):
                    base = attr_chain(func.value)
                    if base in ("self", "cls"):
                        for callee in self._by_name.get(
                                (rel, func.attr), []):
                            if callee.cls is not None:
                                self.edges[info.key].add(callee.key)
        # module-level code (import-time Thread spawns etc.) can also
        # hand out roots
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and id(node) not in in_function:
                for root in self._call_roots(rel, node, node_map):
                    self.roots.add(root.key)
        for info in self.functions.values():
            if info.rel == rel and info.cls is not None \
                    and info.name in ALWAYS_ROOT_METHODS:
                self.roots.add(info.key)

    # ---- queries ----

    def worker_reachable(self) -> set[tuple[str, str]]:
        """Keys of every function reachable from a worker root."""
        seen: set[tuple[str, str]] = set()
        stack = list(self.roots)
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            stack.extend(self.edges.get(key, ()))
        return seen


def build_call_graph(files: Iterable) -> CallGraph:
    """Graph over ``SourceFile``s (anything with ``.rel`` + ``.tree``)."""
    graph = CallGraph()
    for sf in files:
        graph.add_module(sf.rel, sf.tree)
    return graph
