"""Function-granular project call graph (the CB2xx/CB3xx substrate).

The CB204 cross-plane rule needs "can this function run on a
HostPipeline worker thread?"; the CB3xx family (analysis/flow.py) needs
"can this function run under a durability root / a sim scenario?" —
both are *reachability* questions over one interprocedural graph.  This
module builds it from stdlib ``ast`` alone (no imports executed, no
types inferred):

* **Nodes** are every ``def`` / ``async def`` / ``lambda`` in the
  scanned files, keyed ``(rel, qualname)`` where qualname is the dotted
  class/function nesting path (lambdas get ``<lambda>@line:col``).
* **Edges** resolve in two phases: every module's functions and import
  table are collected first, then call expressions link across module
  boundaries —

  - bare names: same-module functions, then ``from X import f``
    bindings (function-level lazy imports count module-wide);
  - ``self.m()`` / ``cls.m()``: same-module methods named ``m``
    (override-coarse on purpose — a base-class dispatch must reach
    every same-named override the module defines);
  - ``mod.f()`` where ``mod`` is an imported project module (any
    spelling: ``import a.b as mod``, ``from a import b``, relative
    imports): functions named ``f`` in that module;
  - ``Cls.m()`` where ``Cls`` was imported from a project module:
    methods named ``m`` in that module;
  - ``recv.m()`` on any other receiver: *import-scoped* method
    resolution — methods named ``m`` in the calling module and in the
    modules it imports (the middle ground between same-module-only,
    which loses every cross-plane hop, and project-wide, which links
    every ``.write()`` to every writer);
  - decorators: a call edge to a decorated function also edges to its
    project-local decorators (the wrapper actually runs), and the
    decorator edges to the function it wraps;
  - callables that *escape* into another execution context —
    ``functools.partial(f, ...)``, ``asyncio.to_thread(f)``,
    ``loop.run_in_executor(None, f)``, ``threading.Thread(target=f)``,
    ``create_task``/``ensure_future`` over a function reference,
    ``call_soon``/``call_later`` callbacks, host-pipeline ``_Job``/
    ``submit``/``run`` callables, ``add_done_callback`` — edge from
    the handing-off function to the callable.

* **Unknown edges** are counted, never silently dropped: a call whose
  callee is a parameter, a call result, a subscript, or an attribute
  chain that resolves to no known function and no external module is
  dynamic dispatch the graph cannot follow.  ``--graph-stats`` surfaces
  the count so precision regressions are visible in the lint report.
* **Worker roots** are the places code hops OFF the event loop onto a
  plain thread: ``threading.Thread(target=...)``, ``asyncio.to_thread``,
  ``run_in_executor``, job callables handed to the host pipeline
  (``_Job(stage, fn)``, ``.submit(stage, fn)``, ``.run(stage, fn)``
  with a string stage), ``add_done_callback`` callbacks, and
  ``HostPipeline._worker`` itself.  ``call_soon_threadsafe`` /
  ``run_coroutine_threadsafe`` callables are explicitly NOT roots —
  that pair is the sanctioned way back onto the loop.

Over-approximation (same-name collisions, overrides) errs toward
flagging, which the shared ``# lint: <slug>-ok <reason>`` machinery can
excuse; the residual under-approximation (calls through stored
callables) is counted as unknown edges and backstopped by the runtime
harnesses the static rules front-run (sanitizer, crash matrix,
determinism pin).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

#: the sanctioned loop re-entry points: callables passed to these are
#: back ON the loop, so they are never worker roots
THREADSAFE_WRAPPERS = ("call_soon_threadsafe", "run_coroutine_threadsafe")

#: method names that are always worker bodies regardless of how they
#: are reached (the scheduler's own run loop)
ALWAYS_ROOT_METHODS = ("_worker",)

#: wrapper tails whose first positional argument is a callable handed
#: to another execution context (edge, but not a worker root)
_CALLBACK_WRAPPERS = ("create_task", "ensure_future", "call_soon",
                      "call_later", "call_at")

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def attr_chain(node: ast.AST) -> str:
    """Dotted name for Name/Attribute chains ('loop.call_soon'), or ''."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclass
class FuncInfo:
    """One function/method/lambda node in the graph."""

    rel: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    cls: Optional[str]  # lexically enclosing class, if any

    @property
    def key(self) -> tuple[str, str]:
        return (self.rel, self.qualname)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def lineno(self) -> int:
        return getattr(self.node, "lineno", 0)


def iter_body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's OWN statements: descend the body but stop at
    nested def/lambda boundaries (those are separate graph nodes —
    their code runs when *they* are called, not when the outer function
    does)."""
    stack = list(ast.iter_child_nodes(fn))
    # the function's own args/defaults evaluate in the caller, skip the
    # nested bodies only
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


@dataclass
class _ModuleImports:
    """One module's import surface, collected tree-wide (function-level
    lazy imports deliberately count module-wide — a lazy hop is still a
    hop the reachability rules must follow)."""

    #: alias -> project module rel ("fsio" -> "utils/fsio.py")
    modules: dict[str, str] = field(default_factory=dict)
    #: bare name -> (project module rel, name) for ``from X import f``
    names: dict[str, tuple[str, str]] = field(default_factory=dict)
    #: alias -> True for imports that resolve OUTSIDE the scanned tree
    #: (stdlib, third-party) — calls through these are external, not
    #: unknown
    external: set[str] = field(default_factory=set)

    def imported_rels(self) -> set[str]:
        return set(self.modules.values()) | {
            rel for rel, _name in self.names.values()}


class CallGraph:
    """Import-aware, function-granular call graph over parsed files.

    Build with :func:`build_call_graph` (two-phase: ``add_module`` for
    every file, then ``finalize``)."""

    def __init__(self) -> None:
        self.functions: dict[tuple[str, str], FuncInfo] = {}
        #: key -> set of callee keys
        self.edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
        #: callee key -> [(caller key, ast.Call at the call site)] for
        #: direct-call edges (CB305 walks these to judge arguments)
        self.call_sites: dict[tuple[str, str],
                              list[tuple[tuple[str, str], ast.Call]]] = {}
        self.roots: set[tuple[str, str]] = set()
        #: (caller, callee) pairs that cross BACK to the loop plane
        #: (call_soon_threadsafe / run_coroutine_threadsafe handoffs):
        #: traversed for general reachability, never by the worker
        #: closure — they are the sanctioned plane crossing CB204
        #: exists to steer code toward
        self.loop_edges: set[tuple[tuple[str, str],
                                   tuple[str, str]]] = set()
        #: caller key -> count of dynamic-dispatch calls the graph
        #: could not resolve ('' key: module-level code)
        self.unknown_edges: dict[tuple[str, str], int] = {}
        #: per (rel, last-name-segment) function lookup for resolution
        self._by_name: dict[tuple[str, str], list[FuncInfo]] = {}
        self._imports: dict[str, _ModuleImports] = {}
        self._trees: dict[str, ast.AST] = {}
        self._node_maps: dict[str, dict] = {}
        #: project-module dotted-path suffixes -> rel, for resolving
        #: absolute imports whatever the package prefix is
        self._module_rels: set[str] = set()

    # ---- derived stats ----

    @property
    def unknown_edge_count(self) -> int:
        return sum(self.unknown_edges.values())

    @property
    def edge_count(self) -> int:
        return sum(len(v) for v in self.edges.values())

    def stats(self) -> dict:
        return {
            "functions": len(self.functions),
            "edges": self.edge_count,
            "worker_roots": len(self.roots),
            "unknown_edges": self.unknown_edge_count,
            "modules": len(self._trees),
        }

    # ---- phase 1: collection ----

    def add_module(self, rel: str, tree: ast.AST) -> None:
        self._trees[rel] = tree
        self._module_rels.add(rel)
        self._node_maps[rel] = self._collect_functions(rel, tree)

    def _add_function(self, info: FuncInfo) -> None:
        self.functions[info.key] = info
        self.edges.setdefault(info.key, set())
        self._by_name.setdefault((info.rel, info.name), []).append(info)

    def _collect_functions(self, rel: str, tree: ast.AST) -> dict:
        """Register every function in ``tree``; returns node -> FuncInfo
        so the edge pass can map callables back to graph nodes."""
        node_map: dict[ast.AST, FuncInfo] = {}

        def visit(node: ast.AST, quals: tuple[str, ...],
                  cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, quals + (child.name,), child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    q = ".".join(quals + (child.name,))
                    info = FuncInfo(rel, q, child, cls)
                    self._add_function(info)
                    node_map[child] = info
                    # nested defs/lambdas belong to no class: calling
                    # self.x() inside them still resolves class-wide
                    visit(child, quals + (child.name,), cls)
                elif isinstance(child, ast.Lambda):
                    q = ".".join(
                        quals + (f"<lambda>@{child.lineno}:"
                                 f"{child.col_offset}",))
                    info = FuncInfo(rel, q, child, cls)
                    self._add_function(info)
                    node_map[child] = info
                    visit(child, quals, cls)
                else:
                    visit(child, quals, cls)

        visit(tree, (), None)
        return node_map

    # ---- import resolution ----

    def _rel_for_module(self, dotted: str, from_rel: str,
                        level: int = 0) -> Optional[str]:
        """Project rel path for a dotted module name, or None when the
        module is outside the scanned tree.  Tries the dotted path as
        given and with leading package segments stripped (the scan root
        is usually the package dir, so ``chunky_bits_tpu.utils.fsio``
        must resolve to ``utils/fsio.py``); relative imports resolve
        against the importing module's package directory."""
        if level > 0:
            base = from_rel.rsplit("/", 1)[0] if "/" in from_rel else ""
            for _ in range(level - 1):
                base = base.rsplit("/", 1)[0] if "/" in base else ""
            prefix = f"{base}/" if base else ""
            parts = dotted.split(".") if dotted else []
            cand = prefix + "/".join(parts)
            for suffix in (".py", "/__init__.py"):
                rel = (cand + suffix) if parts else (cand.rstrip("/")
                                                    + "/__init__.py")
                if rel in self._module_rels:
                    return rel
            return None
        parts = dotted.split(".")
        for start in range(len(parts)):
            cand = "/".join(parts[start:])
            for rel in (f"{cand}.py", f"{cand}/__init__.py"):
                if rel in self._module_rels:
                    return rel
        return None

    def _collect_imports(self, rel: str, tree: ast.AST
                         ) -> _ModuleImports:
        imp = _ModuleImports()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self._rel_for_module(alias.name, rel)
                    bound = alias.asname or alias.name.split(".")[0]
                    if target is not None:
                        # `import a.b.c` binds `a`, but dotted calls
                        # through the full chain resolve via
                        # _rel_for_module at the call site; an asname
                        # binds the leaf module directly
                        if alias.asname is not None:
                            imp.modules[bound] = target
                        else:
                            imp.modules.setdefault(bound, target)
                    else:
                        imp.external.add(bound)
            elif isinstance(node, ast.ImportFrom):
                target = self._rel_for_module(node.module or "", rel,
                                              node.level)
                for alias in node.names:
                    bound = alias.asname or alias.name
                    # `from pkg import mod` may name a submodule:
                    # resolve it FIRST — the parent package's __init__
                    # need not be in the scan for the submodule
                    # binding to be real
                    sub = self._rel_for_module(
                        f"{node.module or ''}.{alias.name}".strip("."),
                        rel, node.level)
                    if sub is not None:
                        imp.modules[bound] = sub
                    elif target is not None:
                        imp.names[bound] = (target, alias.name)
                    else:
                        imp.external.add(bound)
        return imp

    # ---- phase 2: edges + roots ----

    def finalize(self) -> None:
        for rel, tree in self._trees.items():
            self._imports[rel] = self._collect_imports(rel, tree)
        for rel, tree in self._trees.items():
            self._link_module(rel, tree)
        # decorator edges: a project-local decorator's wrapper runs when
        # the decorated function is called, and typically calls it
        for info in list(self.functions.values()):
            node = info.node
            for dec in getattr(node, "decorator_list", ()):
                expr = dec.func if isinstance(dec, ast.Call) else dec
                for target in self._resolve_target(info.rel, expr,
                                                   None)[0]:
                    self.edges.setdefault(target.key, set()).add(
                        info.key)
        for info in self.functions.values():
            if info.cls is not None \
                    and info.name in ALWAYS_ROOT_METHODS:
                self.roots.add(info.key)

    def _params_of(self, fn: ast.AST) -> set[str]:
        args = fn.args
        named = (list(args.posonlyargs) + list(args.args)
                 + list(args.kwonlyargs))
        out = {a.arg for a in named}
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                out.add(extra.arg)
        return out

    def _resolve_target(self, rel: str, expr: ast.AST,
                        params: Optional[set[str]]
                        ) -> tuple[list[FuncInfo], bool]:
        """(candidate functions, unknown?) for a callee expression.

        ``unknown`` is True only for genuinely dynamic dispatch: a
        parameter call, a call-result/subscript call, or an attribute
        chain with no candidates that does not route through a known
        external module."""
        if isinstance(expr, ast.Name):
            name = expr.id
            local = list(self._by_name.get((rel, name), []))
            imp = self._imports.get(rel)
            if imp is not None and name in imp.names:
                target_rel, target_name = imp.names[name]
                local.extend(self._by_name.get(
                    (target_rel, target_name), []))
            if local:
                return local, False
            if params is not None and name in params:
                return [], True  # call through a parameter
            return [], False  # builtin / external name
        if isinstance(expr, ast.Attribute):
            method = expr.attr
            base = attr_chain(expr.value)
            imp = self._imports.get(rel)
            if base in ("self", "cls"):
                cands = [f for f in self._by_name.get((rel, method), [])
                         if f.cls is not None]
                # self.attr calls with no same-module method: stored
                # callables / cross-module bases — dynamic dispatch
                return cands, not cands
            if imp is not None:
                head = base.split(".", 1)[0]
                # full dotted module path (package.mod.func())
                dotted_rel = self._rel_for_module(base, rel) \
                    if base else None
                if dotted_rel is not None:
                    return list(self._by_name.get(
                        (dotted_rel, method), [])), False
                if base in imp.modules:
                    return list(self._by_name.get(
                        (imp.modules[base], method), [])), False
                if base in imp.names:
                    # imported class: methods in its home module
                    target_rel, _cls = imp.names[base]
                    return list(self._by_name.get(
                        (target_rel, method), [])), False
                if head in imp.external or head in imp.modules:
                    return [], False
            # import-scoped instance-method resolution: methods named
            # `method` in this module and its imported project modules
            scope_rels = [rel]
            if imp is not None:
                scope_rels.extend(sorted(imp.imported_rels()))
            cands = []
            for srel in scope_rels:
                cands.extend(
                    f for f in self._by_name.get((srel, method), [])
                    if f.cls is not None)
            if cands:
                return cands, False
            # receiver unresolved and no candidate anywhere in import
            # scope: stdlib object methods land here too — counted as
            # unknown on purpose (honest over dynamic dispatch)
            return [], True
        if isinstance(expr, (ast.Call, ast.Subscript)):
            return [], True  # f()() / table[k]() — dynamic
        return [], False

    def _resolve_callable(self, rel: str, expr: ast.AST,
                          node_map: dict,
                          params: Optional[set[str]]
                          ) -> list[FuncInfo]:
        """Graph nodes a callable *reference* may denote: a lambda is
        itself; ``functools.partial(f, ...)`` unwraps to ``f``;
        names/attributes resolve like call targets."""
        if isinstance(expr, ast.Lambda):
            info = node_map.get(expr)
            return [info] if info is not None else []
        if isinstance(expr, ast.Call):
            tail = attr_chain(expr.func).rsplit(".", 1)[-1]
            if tail == "partial" and expr.args:
                return self._resolve_callable(rel, expr.args[0],
                                              node_map, params)
            return []
        return self._resolve_target(rel, expr, params)[0]

    def _call_handoffs(self, rel: str, call: ast.Call, node_map: dict,
                       params: Optional[set[str]]
                       ) -> Iterator[tuple[FuncInfo, str]]:
        """(callable, kind) pairs referenced by one Call that hands a
        callable to another execution context.  kind is ``'root'``
        (runs on a worker thread), ``'edge'`` (runs, same plane), or
        ``'loop'`` (runs, but back ON the loop — the threadsafe
        crossing, excluded from the worker closure)."""
        chain = attr_chain(call.func)
        tail = chain.rsplit(".", 1)[-1] if chain else ""
        rooted: list[ast.AST] = []
        linked: list[ast.AST] = []
        looped: list[ast.AST] = []
        if tail == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    rooted.append(kw.value)
        elif tail == "to_thread" and call.args:
            rooted.append(call.args[0])
        elif tail == "run_in_executor" and len(call.args) >= 2:
            rooted.append(call.args[1])
        elif tail == "_Job" and len(call.args) >= 2:
            rooted.append(call.args[1])
        elif (tail in ("submit", "run") and len(call.args) >= 2
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            # HostPipeline.submit(stage, fn) / await pipeline.run(stage,
            # fn) — the string stage distinguishes them from
            # concurrent.futures submit(fn, ...) and asyncio.run(coro)
            rooted.append(call.args[1])
        elif tail == "add_done_callback" and call.args:
            # completion callbacks run on whichever thread finishes the
            # job — for pipeline jobs that is a worker
            rooted.append(call.args[0])
        elif tail in THREADSAFE_WRAPPERS and call.args:
            # the sanctioned worker->loop crossing: the callable runs
            # on the loop, so worker-ness must NOT flow through it
            looped.append(call.args[0])
        elif tail in _CALLBACK_WRAPPERS and call.args:
            # loop-side callables: an edge (the code runs), not a root
            linked.append(call.args[0])
        for expr in rooted:
            for info in self._resolve_callable(rel, expr, node_map,
                                               params):
                # an async def handed to a thread only builds a
                # coroutine object there — its body runs on a loop,
                # never the worker, so it cannot seed worker-ness
                if isinstance(info.node, ast.AsyncFunctionDef):
                    yield info, "edge"
                else:
                    yield info, "root"
        for expr in linked:
            for info in self._resolve_callable(rel, expr, node_map,
                                               params):
                yield info, "edge"
        for expr in looped:
            for info in self._resolve_callable(rel, expr, node_map,
                                               params):
                yield info, "loop"

    def _link_call(self, rel: str, caller_key: tuple[str, str],
                   call: ast.Call, node_map: dict,
                   params: Optional[set[str]]) -> None:
        for info, kind in self._call_handoffs(rel, call, node_map,
                                              params):
            if kind == "root":
                self.roots.add(info.key)
            elif kind == "loop":
                self.loop_edges.add((caller_key, info.key))
            self.edges.setdefault(caller_key, set()).add(info.key)
        targets, unknown = self._resolve_target(rel, call.func, params)
        if unknown:
            self.unknown_edges[caller_key] = \
                self.unknown_edges.get(caller_key, 0) + 1
        for info in targets:
            self.edges.setdefault(caller_key, set()).add(info.key)
            self.call_sites.setdefault(info.key, []).append(
                (caller_key, call))
            # calling a decorated function actually calls its wrapper:
            # edge to the project-local decorators too (added in
            # finalize's decorator pass via the reverse direction)

    def _link_module(self, rel: str, tree: ast.AST) -> None:
        node_map = self._node_maps[rel]
        in_function: set[int] = set()
        for info in [i for i in self.functions.values()
                     if i.rel == rel]:
            fn_params = self._params_of(info.node) \
                if not isinstance(info.node, ast.Lambda) \
                else {a.arg for a in info.node.args.args}
            for node in iter_body_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                in_function.add(id(node))
                self._link_call(rel, info.key, node, node_map,
                                fn_params)
        # module-level code (import-time Thread spawns etc.) can also
        # hand out roots; its calls attribute to the ('' qualname)
        # pseudo-caller for unknown-edge accounting
        module_key = (rel, "")
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and id(node) not in in_function:
                for info, kind in self._call_handoffs(
                        rel, node, node_map, None):
                    if kind == "root":
                        self.roots.add(info.key)
                    elif kind == "loop":
                        self.loop_edges.add((module_key, info.key))
                    self.edges.setdefault(module_key, set()).add(
                        info.key)

    # ---- queries ----

    def reachable(self, roots: Iterable[tuple[str, str]]
                  ) -> set[tuple[str, str]]:
        """Keys of every function reachable from ``roots`` (inclusive,
        for roots that are graph nodes)."""
        seen: set[tuple[str, str]] = set()
        stack = [key for key in roots if key in self.functions]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            stack.extend(self.edges.get(key, ()))
        return seen

    def worker_reachable(self) -> set[tuple[str, str]]:
        """Keys of every function whose body can execute on a worker
        thread.  Narrower than ``reachable(roots)`` on two counts:
        loop-crossing edges (callables handed back through
        call_soon_threadsafe / run_coroutine_threadsafe) are not
        traversed, and async defs are never entered — a worker calling
        an ``async def`` only builds a coroutine object; the body runs
        on an event loop."""

        def _is_async(key: tuple[str, str]) -> bool:
            info = self.functions.get(key)
            return info is not None and isinstance(
                info.node, ast.AsyncFunctionDef)

        seen: set[tuple[str, str]] = set()
        stack = [key for key in self.roots
                 if key in self.functions and not _is_async(key)]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            for nxt in self.edges.get(key, ()):
                if (key, nxt) in self.loop_edges or _is_async(nxt):
                    continue
                stack.append(nxt)
        return seen

    def functions_in(self, rel_prefix: str) -> Iterator[FuncInfo]:
        for info in self.functions.values():
            if info.rel.startswith(rel_prefix):
                yield info


def build_call_graph(files: Iterable) -> CallGraph:
    """Graph over ``SourceFile``s (anything with ``.rel`` + ``.tree``)."""
    graph = CallGraph()
    for sf in files:
        graph.add_module(sf.rel, sf.tree)
    graph.finalize()
    return graph
