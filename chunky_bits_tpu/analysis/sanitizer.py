"""Opt-in runtime concurrency sanitizer for the two-plane runtime.

The CB2xx rules prove hazards statically where the call graph can see
them; this module catches the dynamic remainder at runtime, enabled by
``$CHUNKY_BITS_TPU_SANITIZE`` (via ``tunables.sanitize_enabled`` —
CB102) and OFF by default.  Three monitors, all built to the
degrade-never-hang invariant (CLAUDE.md):

* :class:`LoopWatchdog` — a daemon sampling thread heartbeats every
  registered event loop through ``call_soon_threadsafe`` and records a
  *stall* when a running loop fails to service the heartbeat within the
  threshold (a blocked loop = CB201's hazard actually happening).  It
  never blocks on a loop: a dead loop (stopped but not closed) simply
  never completes a heartbeat and records nothing; a closed loop is
  dropped on the ``RuntimeError``.
* :class:`TaskRegistry` — a task factory + loop exception handler pair
  that records every spawned task's creation site and captures the
  "Task was destroyed but it is pending!" / "exception was never
  retrieved" events the stock loop only logs (CB203's hazard at
  runtime).  ``pending_leaks()`` additionally reports live, unfinished
  tasks whose loop already stopped — the leak tier-1's leak-strict mode
  could not previously see.
* :class:`HandoffChecker` — asserts HostPipeline completions land on
  the submitting side: the submit records (loop, thread), the bridge
  callback's resolve verifies it is running on that same loop+thread
  (CB204's contract), and a blocking job wait issued *from* a loop
  thread is recorded as a violation (the sync-wait-on-loop deadlock
  shape).

Activation: :func:`install` swaps in an event-loop policy that
instruments every future loop (and can instrument an existing one via
:meth:`Sanitizer.instrument_loop`); ``HostPipeline`` and the gateway
self-activate when the flag is set.  The hot-path hooks in
``parallel/host_pipeline.py`` reach this module only through
``sys.modules`` — when the sanitizer was never imported, the off path
costs a dict lookup and imports nothing (pinned by
tests/test_sanitizer.py).
"""

from __future__ import annotations

import asyncio
import threading
import time
import traceback
import weakref
from dataclasses import dataclass, field
from typing import Any, Optional

__all__ = [
    "HandoffChecker",
    "LoopWatchdog",
    "Sanitizer",
    "SanitizerReport",
    "TaskRegistry",
    "active",
    "get_monitor",
    "install",
    "report",
    "uninstall",
]


@dataclass
class SanitizerReport:
    """Aggregate findings at report time.  ``stalls`` are advisory
    (CI boxes stall under load); the other three are hard failures for
    the tier-1 sanitize leg."""

    leaked_tasks: list[str] = field(default_factory=list)
    unretrieved_exceptions: list[str] = field(default_factory=list)
    handoff_violations: list[str] = field(default_factory=list)
    stalls: list[str] = field(default_factory=list)

    def ok(self) -> bool:
        return not (self.leaked_tasks or self.unretrieved_exceptions
                    or self.handoff_violations)

    def render(self) -> str:
        lines = [
            f"sanitizer: {len(self.leaked_tasks)} leaked task(s), "
            f"{len(self.unretrieved_exceptions)} unretrieved "
            f"exception(s), {len(self.handoff_violations)} handoff "
            f"violation(s), {len(self.stalls)} loop stall(s) [advisory]"
        ]
        for tag, items in (("LEAKED", self.leaked_tasks),
                           ("UNRETRIEVED", self.unretrieved_exceptions),
                           ("HANDOFF", self.handoff_violations),
                           ("STALL", self.stalls)):
            lines.extend(f"  {tag}: {item}" for item in items)
        return "\n".join(lines)


def _creation_site() -> str:
    """First stack frame outside asyncio/this module — where the task
    was actually spawned."""
    for frame in reversed(traceback.extract_stack(limit=12)):
        fn = frame.filename
        if "asyncio" in fn or fn.endswith("sanitizer.py"):
            continue
        return f"{fn}:{frame.lineno} ({frame.name})"
    return "<unknown>"


class TaskRegistry:
    """Per-process task bookkeeping: creation sites via a task factory,
    lifecycle failures via the loop exception handler."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: weakref(task) -> creation site; the weakref callback removes
        #: its own entry so the registry never pins a task
        self._tasks: dict[weakref.ref, str] = {}
        self._events: list[str] = []

    # ---- loop instrumentation ----

    def install_on_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        loop.set_task_factory(self._factory)
        prev = loop.get_exception_handler()

        def handler(lp: asyncio.AbstractEventLoop,
                    context: dict) -> None:
            self._on_exception(lp, context, prev)

        loop.set_exception_handler(handler)

    def _factory(self, loop: asyncio.AbstractEventLoop, coro: Any,
                 **kwargs: Any) -> asyncio.Task:
        # mirror the stock factory (3.10 calls factory(loop, coro);
        # newer versions add context=) — never alter task semantics
        task = asyncio.Task(coro, loop=loop, **kwargs)
        site = _creation_site()
        with self._lock:
            ref = weakref.ref(task, self._drop)
            self._tasks[ref] = site
        return task

    def _drop(self, ref: weakref.ref) -> None:
        # deliberately lock-free: this runs as a weakref callback,
        # which cyclic GC may fire re-entrantly INSIDE one of this
        # class's locked sections on the same thread — taking the
        # non-reentrant lock there would deadlock the loop thread.
        # A single dict pop is GIL-atomic.
        self._tasks.pop(ref, None)

    def _on_exception(self, loop: asyncio.AbstractEventLoop,
                      context: dict, prev: Any) -> None:
        msg = str(context.get("message", ""))
        captured = ("never retrieved" in msg
                    or "destroyed but it is pending" in msg)
        if captured:
            task = context.get("task") or context.get("future")
            exc = context.get("exception")
            detail = f"{msg}: {task!r}"
            if exc is not None:
                detail += f" exception={exc!r}"
            with self._lock:
                self._events.append(detail)
            # the sanitizer owns reporting for captured events; the
            # default handler would only duplicate them on stderr
            return
        if prev is not None:
            prev(loop, context)
        else:
            loop.default_exception_handler(context)

    # ---- reporting ----

    def events(self) -> list[str]:
        with self._lock:
            return list(self._events)

    def pending_leaks(self) -> list[str]:
        """Live, unfinished tasks whose loop already stopped running —
        nobody can ever await them now."""
        # bounded retry: _drop is lock-free (see above), so a GC pop
        # can race this snapshot and raise "changed size during
        # iteration"
        for _ in range(8):
            try:
                with self._lock:
                    snapshot = list(self._tasks.items())
                break
            except RuntimeError:
                continue
        else:
            snapshot = []
        out = []
        for ref, site in snapshot:
            task = ref()
            if task is None or task.done():
                continue
            loop = task.get_loop()
            if loop.is_closed() or not loop.is_running():
                out.append(f"{task!r} created at {site}")
        return out


class LoopWatchdog:
    """Heartbeat-samples registered loops from a daemon thread and
    records stalls.  Every wait in here is bounded; the thread holds no
    loop resources, so a hung or dead loop can never hang the watchdog
    (or vice versa)."""

    def __init__(self, threshold: float = 1.0,
                 interval: float = 0.25) -> None:
        self.threshold = threshold
        self.interval = interval
        self._lock = threading.Lock()
        #: id(loop) -> (weakref, sent_at, done_flag, reported)
        self._beats: dict[int, list] = {}
        self.stalls: list[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def watch(self, loop: asyncio.AbstractEventLoop) -> None:
        with self._lock:
            self._beats.setdefault(
                id(loop), [weakref.ref(loop), None, None, False])
        self._ensure_thread()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="cb-sanitizer-wd")
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            with self._lock:
                entries = list(self._beats.items())
            for key, entry in entries:
                ref, sent_at, done, reported = entry
                loop = ref()
                if loop is None or loop.is_closed():
                    with self._lock:
                        self._beats.pop(key, None)
                    continue
                now = time.monotonic()
                if sent_at is not None and not done[0]:
                    # only a RUNNING loop that ignores its heartbeat is
                    # stalled; a stopped-but-open loop just idles here
                    if (now - sent_at > self.threshold
                            and loop.is_running() and not reported):
                        entry[3] = True
                        with self._lock:
                            self.stalls.append(
                                f"loop {key:#x} unresponsive for "
                                f">{self.threshold:.2f}s (callback "
                                "blocking the event loop?)")
                    continue
                flag = [False]
                try:
                    loop.call_soon_threadsafe(
                        flag.__setitem__, 0, True)
                except RuntimeError:
                    # closed between the check and the call: drop it
                    with self._lock:
                        self._beats.pop(key, None)
                    continue
                entry[1] = now
                entry[2] = flag
                entry[3] = False

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout)


class HandoffChecker:
    """Asserts host-pipeline completions land on the submitting side
    and that no loop thread sits in a blocking job wait."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.violations: list[str] = []

    def _record(self, message: str) -> None:
        with self._lock:
            self.violations.append(message)

    def submit_token(self) -> tuple:
        return (asyncio.get_running_loop(), threading.get_ident())

    def check_resolve(self, token: tuple) -> None:
        loop, tid = token
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is not loop or threading.get_ident() != tid:
            rid = id(running) if running is not None else 0
            self._record(
                "pipeline completion resolved off the submitting "
                f"side: submitted on loop {id(loop):#x} (thread "
                f"{tid}), resolved on loop {rid:#x} (thread "
                f"{threading.get_ident()})")

    def check_sync_wait(self, where: str) -> None:
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return
        self._record(
            f"blocking {where} on an event-loop thread: the loop "
            "stalls until a worker finishes — await the async API "
            "instead")


class Sanitizer:
    """One installed sanitizer: registry + watchdog + handoff checker
    plus the loop-policy shim that instruments future loops."""

    def __init__(self, watchdog_threshold: float = 1.0) -> None:
        self.tasks = TaskRegistry()
        self.watchdog = LoopWatchdog(threshold=watchdog_threshold)
        self.handoff = HandoffChecker()
        self._prev_policy: Optional[asyncio.AbstractEventLoopPolicy] \
            = None

    def instrument_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        self.tasks.install_on_loop(loop)
        self.watchdog.watch(loop)

    def _install_policy(self) -> None:
        prev = asyncio.get_event_loop_policy()
        sanitizer = self

        class _SanitizingPolicy(type(prev)):  # type: ignore[misc]
            def new_event_loop(self) -> asyncio.AbstractEventLoop:
                loop = super().new_event_loop()
                sanitizer.instrument_loop(loop)
                return loop

        self._prev_policy = prev
        asyncio.set_event_loop_policy(_SanitizingPolicy())

    def close(self) -> None:
        self.watchdog.stop()
        if self._prev_policy is not None:
            asyncio.set_event_loop_policy(self._prev_policy)
            self._prev_policy = None

    def report(self) -> SanitizerReport:
        return SanitizerReport(
            leaked_tasks=self.tasks.pending_leaks(),
            unretrieved_exceptions=self.tasks.events(),
            handoff_violations=list(self.handoff.violations),
            stalls=list(self.watchdog.stalls),
        )


# ---- process-global activation ----
#
# Deliberate process-wide singleton (the sanitizer instruments global
# interpreter state — the loop policy — so two live instances would
# fight); analysis/ is outside CB205's serve-path scope, and the lock
# makes first-use construction single.

_GLOBAL: Optional[Sanitizer] = None
_GLOBAL_LOCK = threading.Lock()


def install(watchdog_threshold: float = 1.0) -> Sanitizer:
    """Install (or return) the process-global sanitizer: future event
    loops are instrumented via the policy; instrument an already-live
    loop explicitly with ``instrument_loop``."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            san = Sanitizer(watchdog_threshold=watchdog_threshold)
            san._install_policy()
            _GLOBAL = san
        return _GLOBAL


def uninstall() -> None:
    """Tear down the global sanitizer (tests): restores the previous
    loop policy and stops the watchdog thread (bounded)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
            _GLOBAL = None


def active() -> Optional[Sanitizer]:
    """The installed sanitizer, or None.  Hot paths call this through
    ``sys.modules.get(...)`` so the off path never imports us."""
    return _GLOBAL


def get_monitor() -> Sanitizer:
    """Install-on-first-use accessor for self-activating components
    (HostPipeline, gateway serve) once ``sanitize_enabled()`` said
    yes."""
    return install()


def report() -> SanitizerReport:
    san = _GLOBAL
    return san.report() if san is not None else SanitizerReport()
