"""CLI: ``python -m chunky_bits_tpu.analysis``.

Exit codes: 0 clean (no violations beyond the baseline), 1 new
violations (or unparseable files — the gate must not go green because
the tree stopped parsing), 2 usage errors.  ``--json`` emits one
machine-readable object (mirrors bench.py's one-line contract).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from chunky_bits_tpu.analysis.core import (
    iter_python_files,
    load_baseline,
    run_analysis,
    write_baseline,
)
from chunky_bits_tpu.analysis.rules import ALL_RULES, rule_family

PACKAGE_ROOT = Path(__file__).resolve().parents[1]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.toml"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m chunky_bits_tpu.analysis",
        description="project-native invariant linter (see analysis/"
                    "__init__.py for the invariant -> rule map)")
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/dirs to scan (default: the chunky_bits_tpu package)")
    parser.add_argument(
        "--root", type=Path, default=PACKAGE_ROOT,
        help="root that rel paths (rule scopes, baseline entries) are "
             "resolved against (default: the package dir)")
    parser.add_argument(
        "--baseline", type=Path, default=DEFAULT_BASELINE,
        help="baseline file of accepted findings "
             "(default: analysis/baseline.toml)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="accept all current findings into the baseline and exit 0")
    parser.add_argument(
        "--prune-baseline", action="store_true",
        help="rewrite the baseline keeping only entries that still "
             "match a finding (drops stale accepts; migrates legacy "
             "fingerprints to scoped ones); never adds entries")
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids or family prefixes to run "
             "(e.g. CB101,CB104 — or CB2 for the whole CB2xx family)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON object instead of text")
    parser.add_argument(
        "--format", choices=("text", "github"), default="text",
        help="text (default) or github workflow-annotation lines "
             "(::error file=...) for new violations/errors")
    parser.add_argument(
        "--graph-stats", action="store_true",
        help="also report call-graph statistics (functions/edges/"
             "worker roots/unknown-edge count) and CFG totals "
             "(functions/blocks/edges/dataflow summaries) so graph "
             "precision regressions show up in the lint report")
    parser.add_argument(
        "--explain", metavar="RULE",
        help="print the full rationale + fix pattern for a rule id, "
             "family prefix (CB3), or slug, then exit")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.explain:
        want = args.explain.strip()
        matched = [r for r in ALL_RULES
                   if r.id.upper().startswith(want.upper())
                   or r.slug == want.lower()]
        if not matched:
            parser.error(f"--explain: no rule matches {want!r}")
        for i, rule in enumerate(matched):
            if i:
                print()
            doc = (rule.__doc__ or "(no rationale recorded)").strip()
            print(f"{rule.id} [{rule.slug}] — {rule.description}")
            if rule.paths:
                print(f"scope: {', '.join(rule.paths)}")
            print()
            print(doc)
        return 0

    rules = ALL_RULES
    if args.select:
        # empty tokens (trailing/doubled commas) would prefix-match
        # every rule and silently widen the scan — drop them, and
        # error when nothing real remains
        wanted = {r.strip().upper() for r in args.select.split(",")
                  if r.strip()}
        if not wanted:
            parser.error("--select given but no rule ids in it")
        # a token selects every rule id it prefixes, so CB2 selects the
        # whole CB2xx family and CB101 selects exactly itself
        unknown = {w for w in wanted
                   if not any(r.id.startswith(w) for r in ALL_RULES)}
        if unknown:
            parser.error(
                f"unknown rule ids: {', '.join(sorted(unknown))}")
        rules = tuple(r for r in ALL_RULES
                      if any(r.id.startswith(w) for w in wanted))

    if args.list_rules:
        from chunky_bits_tpu.analysis.rules import FAMILY_HAZARDS

        families: dict[str, list] = {}
        for rule in rules:
            families.setdefault(rule.family, []).append(rule)
        for family in sorted(families):
            hazard = FAMILY_HAZARDS.get(family, "")
            print(f"{family} — {hazard}" if hazard else family)
            for rule in families[family]:
                print(f"  {rule.id}  {rule.slug:18s} {rule.description}")
        return 0

    files = None
    if args.paths:
        files = []
        for p in args.paths:
            if p.is_dir():
                files.extend(iter_python_files(p))
            elif p.exists():
                files.append(p)
            else:
                parser.error(f"no such path: {p}")

    stats: dict | None = {} if args.graph_stats else None
    violations, errors = run_analysis(args.root, rules, files=files,
                                      stats=stats)

    if args.write_baseline:
        if args.select or files is not None:
            # a restricted scan sees only a subset of findings; writing
            # it out would silently drop every accepted entry outside
            # the subset and fail the next full gate run for everyone
            parser.error("--write-baseline requires a full scan "
                         "(drop --select and explicit paths)")
        if errors:
            # same hazard as above: an unparseable file's accepted
            # findings are absent from this scan, so writing now would
            # drop them and re-fail the gate once the file is fixed
            for err in errors:
                print(f"ERROR {err}", file=sys.stderr)
            parser.error("--write-baseline refused: the scan had file "
                         "errors (fix them first)")
        write_baseline(args.baseline, violations)
        print(f"wrote {len(violations)} accepted finding(s) to "
              f"{args.baseline}")
        return 0

    if args.prune_baseline:
        # same refusal logic as --write-baseline: a restricted or
        # error-laden scan cannot distinguish "stale" from "not
        # scanned", and pruning on it would drop live accepts
        if args.select or files is not None:
            parser.error("--prune-baseline requires a full scan "
                         "(drop --select and explicit paths)")
        if errors:
            for err in errors:
                print(f"ERROR {err}", file=sys.stderr)
            parser.error("--prune-baseline refused: the scan had file "
                         "errors (fix them first)")
        try:
            baseline = load_baseline(args.baseline)
        except ValueError as err:
            parser.error(str(err))
        kept = [v for v in violations if set(v.keys()) & baseline]
        matched = baseline & {k for v in kept for k in v.keys()}
        dropped = len(baseline) - len(matched)
        write_baseline(args.baseline, kept)
        print(f"kept {len(kept)} accepted finding(s), dropped "
              f"{dropped} stale entr(y/ies) in {args.baseline}")
        return 0

    try:
        baseline = set() if args.no_baseline \
            else load_baseline(args.baseline)
    except ValueError as err:
        parser.error(str(err))
    # a finding matches through its scoped fingerprint OR the legacy
    # no-scope spelling (pre-migration baselines keep working)
    new = [v for v in violations
           if not (set(v.keys()) & baseline)]
    matched_entries = baseline & {k for v in violations
                                  for k in v.keys()}
    baselined = len(violations) - len(new)
    stale = len(baseline) - len(matched_entries)

    if args.json:
        out = {
            "new": [{**v.__dict__, "rule_family": rule_family(v.rule)}
                    for v in new],
            "baselined": baselined,
            "stale_baseline_entries": stale,
            "errors": errors,
            "ok": not new and not errors,
        }
        if stats is not None:
            out["graph"] = stats
        print(json.dumps(out))
        return 1 if (new or errors) else 0

    if args.format == "github":
        # workflow-annotation lines; paths are emitted relative to the
        # process cwd (the repo checkout in CI) so the annotations
        # attach to the right files in the diff view
        try:
            prefix = args.root.resolve().relative_to(
                Path.cwd().resolve()).as_posix()
        except ValueError:
            prefix = ""
        for err in errors:
            print("::error title=chunky-bits-tpu analysis::"
                  f"{_annotation_escape(err)}")
        for v in new:
            loc = f"{prefix}/{v.path}" if prefix else v.path
            print(f"::error file={loc},line={v.line},col={v.col},"
                  f"title={v.rule} [{v.slug}]::"
                  f"{_annotation_escape(v.message)}")
    else:
        for err in errors:
            print(f"ERROR {err}")
        for v in new:
            print(v.render())
            print(f"    {v.snippet}")
    summary = (f"{len(new)} new violation(s), {baselined} baselined, "
               f"{stale} stale baseline entr(y/ies), "
               f"{len(errors)} file error(s)")
    if stats is not None:
        summary += (f"; graph: {stats.get('functions', 0)} functions, "
                    f"{stats.get('edges', 0)} edges, "
                    f"{stats.get('worker_roots', 0)} worker roots, "
                    f"{stats.get('unknown_edges', 0)} unknown edges"
                    f"; cfg: {stats.get('cfg_functions', 0)} functions, "
                    f"{stats.get('cfg_blocks', 0)} blocks, "
                    f"{stats.get('cfg_edges', 0)} edges, "
                    f"{stats.get('dataflow_summaries', 0)} summaries")
    if new or errors:
        print(f"FAIL: {summary}")
        return 1
    print(f"ok: {summary}")
    return 0


def _annotation_escape(text: str) -> str:
    """GitHub workflow-command data escaping (%, CR, LF)."""
    return (text.replace("%", "%25")
                .replace("\r", "%0D")
                .replace("\n", "%0A"))


if __name__ == "__main__":
    sys.exit(main())
