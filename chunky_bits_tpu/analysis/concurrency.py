"""CB2xx — concurrency-hazard rules for the two-plane runtime.

The host plane is genuinely concurrent since the shared pipeline
(parallel/host_pipeline.py) started feeding the asyncio gateway: daemon
worker threads complete jobs whose waiters live on event loops, and the
per-loop shared batchers/caches (ops/batching.py, file/chunk_cache.py)
are lock-free only because all their bookkeeping stays on one loop
thread.  The CB1xx rules check single-function invariants; this family
checks the hazards that cross those lines:

- CB201 ``async-blocking``   — a sync blocking call (``time.sleep``,
  file/socket I/O, ``subprocess``) inside ``async def`` stalls every
  request on the loop, not just its own.
- CB202 ``lock-across-await`` — a ``threading.Lock`` held across an
  ``await`` parks the loop thread in a sync lock while the lock owner
  may need the loop to progress: classic two-plane deadlock.
- CB203 ``task-leak``        — a dropped ``create_task`` result is a
  task nobody awaits: its exception is swallowed at GC and tier-1's
  leak-strict mode can't see it.
- CB204 ``cross-plane``      — code reachable from HostPipeline worker
  bodies (see ``callgraph.py``) touching loop-bound state (``loop.
  call_soon``, ``asyncio.Event.set``, methods of ``LOOP_BOUND``-tagged
  classes) without going through ``call_soon_threadsafe`` /
  ``run_coroutine_threadsafe`` corrupts single-loop invariants.
- CB205 ``loop-shared``      — module/class-level mutable state in the
  serve-path packages outlives and spans event loops; per-loop
  singletons use the established loop-keyed pattern
  (``Cluster._encode_batcher``-style WeakKeyDictionary) or justify
  process-wide sharing inline.

All stdlib-``ast``, same suppression/baseline machinery as CB1xx, runs
with the device tunnel down.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from chunky_bits_tpu.analysis.callgraph import (
    THREADSAFE_WRAPPERS,
    attr_chain,
    iter_body_nodes,
)
from chunky_bits_tpu.analysis.rules import Finding, Rule

#: the serve-path packages whose shared objects are per-event-loop by
#: convention (cluster.py hands out batchers/caches loop-keyed);
#: cluster/scrub.py and cluster/repair.py ride along — the scrub
#: daemon's task/counters and the repair planner's metered I/O are
#: exactly the loop/thread-handoff shape this family polices
#: obs/ rides along: the metrics registry and trace buffer ARE shared
#: process-wide by design — the rule makes each such site say so
#: inline instead of growing silently
LOOP_SCOPED_PATHS = ("gateway/", "file/", "parallel/", "obs/",
                     "cluster/scrub.py", "cluster/repair.py")

#: class-body marker the CB204 pass reads: every public method of a
#: ``LOOP_BOUND = True`` class must only ever run on the owning loop's
#: thread (see ops/batching.py, file/chunk_cache.py)
LOOP_BOUND_ATTR = "LOOP_BOUND"


def _last(chain: str) -> str:
    return chain.rsplit(".", 1)[-1] if chain else ""


# ---- shared binding tables -------------------------------------------------
#
# Name-based, module-coarse tracking of what a variable/attribute was
# constructed as.  ``self.X = threading.Event()`` records attr name X;
# a later ``anything.X.set()`` resolves X through the table.  Collisions
# across classes err toward the *threading* kinds (which the rules treat
# as safe), so a coarse match can only lose findings, never invent them
# for thread-safe primitives.

_THREADING_LOCKS = ("Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore")
_LOOP_BOUND_CTORS = {
    "asyncio.Event": "aio_event",
    "asyncio.Queue": "aio_queue",
    "asyncio.Condition": "aio_cond",
    "asyncio.Lock": "aio_lock",
    "asyncio.Future": "aio_future",
}


def _import_map(tree: ast.AST) -> dict[str, str]:
    """Bare name -> source module for ``from X import Y`` bindings, so
    ``Event()`` disambiguates between threading and asyncio."""
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = node.module
    return out


def _ctor_kind(value: ast.AST, imports: dict[str, str]) -> str:
    """Classify a constructor-call RHS: 'lock', 'thread_event',
    'aio_event', 'aio_future', ... or ''."""
    if not isinstance(value, ast.Call):
        return ""
    chain = attr_chain(value.func)
    if not chain:
        return ""
    if "." not in chain:
        src = imports.get(chain, "")
        if src:
            chain = f"{src}.{chain}"
    if chain.startswith("threading."):
        tail = _last(chain)
        if tail in _THREADING_LOCKS:
            return "lock"
        if tail == "Event":
            return "thread_event"
        return ""
    if chain in _LOOP_BOUND_CTORS:
        return _LOOP_BOUND_CTORS[chain]
    if _last(chain) == "create_future":
        return "aio_future"
    return ""


def _binding_table(tree: ast.AST, imports: dict[str, str]
                   ) -> dict[str, str]:
    """name-or-attr-name -> ctor kind, module-wide."""
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        kind = _ctor_kind(value, imports)
        if not kind:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                table.setdefault(tgt.id, kind)
            elif isinstance(tgt, ast.Attribute):
                table.setdefault(tgt.attr, kind)
    return table


# ---- CB201 ----------------------------------------------------------------

class AsyncBlockingCallRule(Rule):
    """CB201 — the event loop must never execute a sync blocking call.

    One stalled callback stalls every in-flight request on that loop
    (gateway GET/PUT, batcher drains, cache singleflight waiters).  The
    watchlist is the sync-API class — ``time.sleep``, direct ``open``,
    sync filesystem metadata ops, ``subprocess``, sync sockets/HTTP;
    unbounded ``Future.result()``/``queue.get()`` waits are CB101's.
    The fix is a hop: ``asyncio.to_thread``, the host pipeline's
    ``run()``, or ``loop.run_in_executor``.  A deliberately-inline fast
    syscall records why with ``# lint: async-blocking-ok <reason>``.
    Nested sync ``def``s inside an ``async def`` are exempt — they run
    wherever they are shipped (usually a worker), not on the loop.
    """

    id = "CB201"
    slug = "async-blocking"
    description = ("no sync blocking calls (sleep/file/socket/"
                   "subprocess) inside async def")

    NAME_CALLS = ("open",)
    #: exact dotted chains
    ATTR_CALLS = frozenset((
        "time.sleep",
        "os.system", "os.popen",
        "os.stat", "os.listdir", "os.scandir", "os.makedirs",
        "os.mkdir", "os.remove", "os.unlink", "os.replace",
        "os.rename", "os.rmdir", "os.chmod", "os.truncate",
        "os.path.exists", "os.path.isfile", "os.path.isdir",
        "os.path.islink", "os.path.getsize", "os.path.getmtime",
        "socket.create_connection", "socket.getaddrinfo",
        "socket.gethostbyname",
        "urllib.request.urlopen",
    ))
    #: flagged by chain prefix
    PREFIX_CALLS = ("subprocess.", "shutil.", "requests.", "os.spawn")
    #: pathlib-style blocking tails, receiver-agnostic
    TAIL_CALLS = frozenset((
        "read_text", "read_bytes", "write_text", "write_bytes",
    ))

    def _blocking(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            return func.id if func.id in self.NAME_CALLS else None
        chain = attr_chain(func)
        if chain in self.ATTR_CALLS:
            return chain
        if any(chain.startswith(p) for p in self.PREFIX_CALLS):
            return chain
        if isinstance(func, ast.Attribute) \
                and func.attr in self.TAIL_CALLS:
            return f".{func.attr}"
        return None

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in iter_body_nodes(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = self._blocking(sub)
                if name is not None:
                    yield (sub.lineno, sub.col_offset,
                           f"sync blocking call {name}() inside async "
                           f"def {node.name}() stalls the event loop; "
                           "hop via asyncio.to_thread / the host "
                           "pipeline, or justify with "
                           "`# lint: async-blocking-ok <reason>`")


def _first_suspension_outside_nested(stmt: ast.AST
                                     ) -> Optional[ast.AST]:
    """First suspension point under ``stmt`` that executes as part of
    ``stmt`` itself: ``await``, plus the implicit suspensions of
    ``async for`` and ``async with``.  Nested def/lambda subtrees
    (including ``stmt`` being one) are skipped: their awaits run when
    they are called."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return node
        stack.extend(ast.iter_child_nodes(node))
    return None


# ---- CB202 ----------------------------------------------------------------

class LockAcrossAwaitRule(Rule):
    """CB202 — a ``threading.Lock`` must not be held across ``await``.

    While the coroutine is suspended the loop thread may run any other
    callback; one that needs the same sync lock blocks the whole loop
    — and if releasing the lock requires the loop to progress, that is
    a deadlock, not a stall.  Covers the ``with <lock>:`` idiom over
    locks recognized by the module-wide binding table (``threading.
    Lock/RLock/Condition/Semaphore`` assigned to names or ``self``
    attributes); suspension points are ``await`` plus the implicit
    ones of ``async for`` / ``async with``.  Hold the lock only around sync sections, or use an
    ``asyncio.Lock``; a provably-awaitless critical section that still
    trips the table records why with
    ``# lint: lock-across-await-ok <reason>``.
    """

    id = "CB202"
    slug = "lock-across-await"
    description = "no threading.Lock held across an await"

    def check(self, sf) -> Iterator[Finding]:
        imports = _import_map(sf.tree)
        table = _binding_table(sf.tree, imports)
        locks = {name for name, kind in table.items() if kind == "lock"}
        if not locks:
            return
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in iter_body_nodes(fn):
                if not isinstance(node, ast.With):
                    continue
                held = None
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func  # lock.acquire()-style guards
                    tail = _last(attr_chain(expr))
                    if tail in locks:
                        held = tail
                        break
                if held is None:
                    continue
                found = None
                for inner in node.body:
                    # nested def/lambda bodies excluded: they await
                    # when *they* run, not while this lock is held
                    found = _first_suspension_outside_nested(inner)
                    if found is not None:
                        break
                if found is not None:
                    yield (found.lineno, found.col_offset,
                           f"suspension point while holding threading "
                           f"lock '{held}' stalls the event loop (and "
                           "can deadlock it); release before "
                           "awaiting or use asyncio.Lock, else "
                           "justify with "
                           "`# lint: lock-across-await-ok <reason>`")


# ---- CB203 ----------------------------------------------------------------

class FireAndForgetTaskRule(Rule):
    """CB203 — every spawned task needs an owner.

    A ``create_task``/``ensure_future`` result dropped on the floor is
    a task nobody awaits and nobody cancels: its exception is reported
    only at GC (if ever) and a still-pending one leaks past loop
    teardown — the exact classes the runtime sanitizer counts.  Store
    it, await it, or give it a done-callback; a deliberately detached
    task records its lifecycle argument with
    ``# lint: task-leak-ok <reason>``.
    """

    id = "CB203"
    slug = "task-leak"
    description = ("create_task/ensure_future results must be stored, "
                   "awaited, or given a done-callback")

    SPAWNERS = ("create_task", "ensure_future")

    def check(self, sf) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Expr):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            tail = _last(attr_chain(call.func))
            if tail in self.SPAWNERS:
                yield (call.lineno, call.col_offset,
                       f"{tail}() result dropped: the task leaks and "
                       "its exception is swallowed — store/await it or "
                       "add a done-callback, else justify with "
                       "`# lint: task-leak-ok <reason>`")


# ---- CB204 ----------------------------------------------------------------

class CrossPlaneHandoffRule(Rule):
    """CB204 — worker-thread code re-enters the loop only through the
    threadsafe doors.

    Built on the function-granular call graph (callgraph.py), shared
    with the CB3xx family through the per-run ProjectContext: from the
    set of functions reachable off-loop (HostPipeline worker bodies,
    thread targets, job callables, done-callbacks) it flags touches of
    loop-bound state — ``loop.call_soon``/``call_later``/``call_at``,
    ``set``/``clear`` on an ``asyncio.Event``, ``set_result``/
    ``set_exception`` on a loop future, and any method call on an
    object constructed from a ``LOOP_BOUND = True`` class (the
    batchers, the chunk cache).  The sanctioned crossings are
    ``loop.call_soon_threadsafe`` and ``asyncio.
    run_coroutine_threadsafe``; anything else mutates single-loop
    bookkeeping from the wrong thread.  A site that is safe for a
    structural reason the graph cannot see records it with
    ``# lint: cross-plane-ok <reason>``.
    """

    id = "CB204"
    slug = "cross-plane"
    description = ("worker-reachable code must cross to the event loop "
                   "via call_soon_threadsafe/run_coroutine_threadsafe")
    project = True

    LOOP_ONLY_API = ("call_soon", "call_later", "call_at")

    def check(self, sf) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError("project rule: use check_project")

    # -- project-wide tables --

    def _loop_bound_classes(self, sfs) -> set[str]:
        """Names of classes tagged LOOP_BOUND = True, plus subclasses
        (resolved by base-name to a fixpoint across the scanned set)."""
        tagged: set[str] = set()
        bases: dict[str, set[str]] = {}
        for sf in sfs:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases.setdefault(node.name, set()).update(
                    _last(attr_chain(b)) for b in node.bases)
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and any(isinstance(t, ast.Name)
                                    and t.id == LOOP_BOUND_ATTR
                                    for t in stmt.targets)
                            and isinstance(stmt.value, ast.Constant)
                            and stmt.value.value is True):
                        tagged.add(node.name)
        while True:
            grown = {cls for cls, bs in bases.items()
                     if bs & tagged} - tagged
            if not grown:
                return tagged
            tagged |= grown

    def _instance_table(self, sfs, classes: set[str]) -> set[str]:
        """Names/attr-names bound to instances of loop-bound classes."""
        out: set[str] = set()
        for sf in sfs:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    targets, value = [node.target], node.value
                else:
                    continue
                if not (isinstance(value, ast.Call)
                        and _last(attr_chain(value.func)) in classes):
                    continue
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
                    elif isinstance(tgt, ast.Attribute):
                        out.add(tgt.attr)
        return out

    def check_project(self, sfs, ctx) -> Iterator[tuple]:
        graph = ctx.graph
        reachable = graph.worker_reachable()
        if not reachable:
            return
        loop_bound = self._loop_bound_classes(sfs)
        instances = self._instance_table(sfs, loop_bound)
        bindings: dict[str, str] = {}
        for sf in sfs:
            table = _binding_table(sf.tree, _import_map(sf.tree))
            for name, kind in table.items():
                # threading kinds win collisions: a coarse match may
                # only lose findings, never flag a thread-safe primitive
                if bindings.get(name, "").startswith("thread") \
                        or bindings.get(name) == "lock":
                    continue
                bindings[name] = kind
        by_rel = {sf.rel: sf for sf in sfs}
        for key in sorted(reachable):
            info = graph.functions.get(key)
            if info is None or info.rel not in by_rel:
                continue
            exempt = self._threadsafe_args(info.node)
            for node in iter_body_nodes(info.node):
                if not isinstance(node, ast.Call) or node in exempt:
                    continue
                hit = self._loop_bound_touch(node, bindings,
                                             instances)
                if hit is not None:
                    yield (info.rel, node.lineno, node.col_offset,
                           f"{hit} from worker-reachable "
                           f"{info.qualname}(): cross to the loop via "
                           "call_soon_threadsafe/"
                           "run_coroutine_threadsafe, or justify with "
                           "`# lint: cross-plane-ok <reason>`")

    def _threadsafe_args(self, fn: ast.AST) -> set:
        """Call nodes nested in the arguments of a threadsafe wrapper
        (``run_coroutine_threadsafe(cache.get(...), loop)``) are the
        sanctioned crossing itself."""
        out: set = set()
        for node in iter_body_nodes(fn):
            if isinstance(node, ast.Call) and _last(
                    attr_chain(node.func)) in THREADSAFE_WRAPPERS:
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Call):
                            out.add(sub)
        return out

    def _loop_bound_touch(self, call: ast.Call, bindings: dict,
                          instances: set) -> Optional[str]:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        method = func.attr
        if method in THREADSAFE_WRAPPERS:
            return None
        if method in self.LOOP_ONLY_API:
            return f"loop.{method}() (not the _threadsafe variant)"
        recv = _last(attr_chain(func.value))
        kind = bindings.get(recv, "")
        if method in ("set", "clear") and kind == "aio_event":
            return f"asyncio.Event '{recv}'.{method}()"
        if method in ("set_result", "set_exception") \
                and kind == "aio_future":
            return f"loop future '{recv}'.{method}()"
        if method in ("put_nowait", "get_nowait") \
                and kind == "aio_queue":
            return f"asyncio.Queue '{recv}'.{method}()"
        if recv in instances and not method.startswith("__"):
            return (f"loop-bound method '{recv}.{method}()' "
                    "(LOOP_BOUND class)")
        return None


# ---- CB205 ----------------------------------------------------------------

class LoopSharedStateRule(Rule):
    """CB205 — serve-path singletons are per-event-loop, not global.

    Module- and class-level mutable containers in ``gateway/``,
    ``file/``, ``parallel/`` are shared by every loop (and every
    worker thread) in the process; the codebase's pattern for shared
    serve-path state is loop-keyed handout from the owning object
    (``Cluster._encode_batcher``-style WeakKeyDictionary per loop).
    Loop-bound asyncio primitives at module/class level are worse
    still: they bind to whichever loop touches them first.  Deliberate
    process-wide state (a lock-guarded singleton like
    ``host_pipeline._SHARED``, an immutable registry) records why with
    ``# lint: loop-shared-ok <reason>``.  Thread-safe primitives
    (``threading.Lock``/``Event``/``local``) and immutables pass.
    """

    id = "CB205"
    slug = "loop-shared"
    description = ("no module/class-level mutable shared state in "
                   "gateway/, file/, parallel/ without the loop-keyed "
                   "pattern")
    paths = LOOP_SCOPED_PATHS

    MUTABLE_CTORS = frozenset((
        "dict", "list", "set", "bytearray", "OrderedDict",
        "defaultdict", "deque", "Counter", "WeakKeyDictionary",
        "WeakValueDictionary", "WeakSet", "Queue", "LifoQueue",
        "SimpleQueue",
    ))
    LOOP_BOUND_CTORS = frozenset((
        "asyncio.Event", "asyncio.Lock", "asyncio.Queue",
        "asyncio.Condition", "asyncio.Semaphore",
    ))
    SAFE_CTORS = frozenset((
        "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
        "Event", "local", "frozenset", "tuple", "MappingProxyType",
    ))

    def _mutable_value(self, value: ast.AST,
                       imports: dict[str, str]) -> Optional[str]:
        if isinstance(value, (ast.Dict, ast.DictComp)):
            return "dict literal"
        if isinstance(value, (ast.List, ast.ListComp)):
            return "list literal"
        if isinstance(value, (ast.Set, ast.SetComp)):
            return "set literal"
        if not isinstance(value, ast.Call):
            return None
        chain = attr_chain(value.func)
        if "." not in chain and imports.get(chain):
            chain = f"{imports[chain]}.{chain}"
        tail = _last(chain)
        if chain in self.LOOP_BOUND_CTORS or (
                chain.startswith("asyncio.")
                and tail in ("Event", "Lock", "Queue", "Condition",
                             "Semaphore")):
            return f"loop-bound {chain}()"
        if chain.startswith("threading.") or tail in self.SAFE_CTORS:
            return None
        if tail in self.MUTABLE_CTORS:
            return f"{tail}()"
        return None

    def _scan_body(self, body, where: str,
                   imports: dict[str, str]) -> Iterator[Finding]:
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names or all(n.startswith("__") and n.endswith("__")
                                for n in names):
                continue  # __all__ / __slots__ etc.
            desc = self._mutable_value(value, imports)
            if desc is not None:
                yield (stmt.lineno, stmt.col_offset,
                       f"{where} mutable shared state "
                       f"{'/'.join(names)} = {desc}: shared across "
                       "event loops and worker threads — use the "
                       "loop-keyed handout pattern "
                       "(Cluster._encode_batcher-style) or justify "
                       "with `# lint: loop-shared-ok <reason>`")

    def check(self, sf) -> Iterator[Finding]:
        imports = _import_map(sf.tree)
        yield from self._scan_body(sf.tree.body, "module-level",
                                   imports)
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                yield from self._scan_body(
                    node.body, f"class-level ({node.name})", imports)


CONCURRENCY_RULES: tuple[Rule, ...] = (
    AsyncBlockingCallRule(),
    LockAcrossAwaitRule(),
    FireAndForgetTaskRule(),
    CrossPlaneHandoffRule(),
    LoopSharedStateRule(),
)
