"""CB4xx — resource-lifetime & deadline-propagation rules (CFG + dataflow).

The Rust reference gets these proofs for free: RAII closes every
fd/flock/mmap on every path out of a scope, and ownership makes leaks
structural errors.  This Python/asyncio rebuild paid twice for their
absence — the ``to_thread(open)`` orphaned-fd cancellation leak and the
unreaped reader tasks were both found *dynamically* (soak flakes, the
CB3xx sweep), not by construction.  This family machine-checks the
discipline over the statement-granular CFGs of ``analysis/cfg.py``:

- CB401 ``fd-leak``       — an acquired handle (``open``/opener
  results, ``os.open``/``fdopen``, ``mmap``, ``socket``, the fsio-seam
  ``open``) must reach a release on EVERY path out of the acquiring
  scope, including the exception and cancellation paths.  Release =
  ``.close()``, custody transfer (returned/yielded, stored into an
  attribute/container, passed to a callee — ``aio.open_in_thread``'s
  closer contract is the async-plane shape), or a ``with`` block.
- CB402 ``lock-discipline`` — ``threading.Lock.acquire()`` /
  ``fcntl.flock(fd, LOCK_EX|LOCK_SH)`` must pair with ``release()`` /
  ``flock(fd, LOCK_UN)`` on every path.  Prefer ``with lock:`` — the
  interpreter then proves the pairing instead of this rule.
- CB403 ``task-custody``  — the CFG-precise upgrade of the syntactic
  CB203: a task assigned from ``create_task``/``ensure_future`` must be
  stored, awaited, or cancelled-AND-awaited on every path out of the
  creating scope (awaiting observes the cancel, so "awaited" covers
  both).  CB203 catches the dropped-expression shape; this rule catches
  the assigned-then-leaked-on-the-error-path shape.
- CB404 ``unbounded-deadline`` — the interprocedural lift of the
  per-module CB101: every CB101-shaped await in code reachable from the
  serving/dispatch/scrub roots must be bounded at SOME frame — a
  ``wait_for``/``run_bounded_dispatch`` at the site or wrapping a call
  on every root path.  Call edges whose every recorded site sits inside
  a bounding wrapper are not traversed, so a deadline proven upstream
  clears the whole subtree ("degrade, never hang" as a whole-program
  property, not a path-list).
- CB405 ``metered-io``    — the scrub/repair exact-metering contract:
  inside ``cluster/scrub.py``/``cluster/repair.py``, every chunk-byte
  ``.read()``/``.write()`` reachable from the scrub/repair roots must
  be dominated by a ``TokenBucket.take()`` charge (must-dataflow; each
  charge covers exactly one I/O — a second read after one ``take``
  re-flags).  A function whose every in-scope call site is dominated by
  a charge is *entered metered* (per-function summaries composed
  through the call graph to fixpoint — the first interprocedural
  dataflow; CB3xx is reachability-only).  Metadata-plane reads are the
  control plane, not chunk I/O, and are exempt by receiver.

Same machinery as every family: suppress inline with
``# lint: <slug>-ok <reason>``; project rules share the per-run
:class:`~chunky_bits_tpu.analysis.reachability.ProjectContext` (call
graph + memoized CFGs — ``--graph-stats`` reports the CFG totals).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from chunky_bits_tpu.analysis.callgraph import attr_chain, iter_body_nodes
from chunky_bits_tpu.analysis.cfg import (
    CFG,
    K_STMT,
    dataflow,
    stmt_expressions,
)
from chunky_bits_tpu.analysis.rules import (
    Finding,
    Rule,
    UnboundedAwaitRule,
    _parents,
)

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


# ---- expression helpers (header-only: a compound statement's CFG node
# ---- evaluates its header; body statements have their own nodes) ----

def _exprs_under(stmt: ast.AST) -> Iterator[ast.AST]:
    """AST nodes evaluated AT this CFG node, nested defs excluded."""
    for expr in stmt_expressions(stmt):
        stack = [expr]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_DEFS + (ast.Lambda,)):
                    continue
                stack.append(child)


def _names_under(expr: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


def _rebound_names(stmt: ast.AST) -> set[str]:
    """Local names this statement rebinds (or deletes) — old facts for
    them die here; ``with ... as f`` and ``for f in ...`` count."""
    out: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [item.optional_vars for item in stmt.items
                   if item.optional_vars is not None]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                out.add(node.id)
    return out


def _common_escapes(stmt: ast.AST) -> set[str]:
    """Names whose custody leaves this scope at ``stmt``: call
    arguments (the callee owns it now — ``closer(f)``,
    ``tasks.append(t)``, ``gather(t)``), returned/yielded values,
    values stored through attribute/subscript targets, plain aliases,
    and ``with`` context expressions.  Receivers (``f.seek()``) are
    USE, not custody — they stay tracked."""
    out: set[str] = set()
    for node in _exprs_under(stmt):
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value for kw
                                          in node.keywords]:
                out |= _names_under(arg)
        elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            out |= _names_under(node.value)
        elif isinstance(node, ast.withitem):
            out |= _names_under(node.context_expr)
    if isinstance(stmt, ast.Return) and stmt.value is not None:
        out |= _names_under(stmt.value)
    if isinstance(stmt, ast.Assign):
        if isinstance(stmt.value, ast.Name):
            # `self._f = x` / `d[k] = x` / `y = x`: custody moved
            out.add(stmt.value.id)
        elif any(not isinstance(t, ast.Name) for t in stmt.targets):
            # storing THROUGH an attribute/subscript/tuple target
            # transfers custody of the stored names too —
            # `self._sessions[k] = (ref, sess, gen, primer)` owns primer
            out |= _stored_names(stmt.value)
    return out


def _stored_names(expr: ast.AST) -> set[str]:
    """Names whose VALUE is being stored by an assignment — call
    receivers (``f.read()``) and attribute bases are use, not custody,
    so they stay tracked."""
    out: set[str] = set()
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            stack.extend(node.args)
            stack.extend(kw.value for kw in node.keywords)
            continue
        if isinstance(node, ast.Attribute):
            continue
        if isinstance(node, ast.Name):
            out.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return out


# ---- the shared leak query ----

class _ResourceSpec:
    """One resource kind: how it is acquired and released."""

    #: enclosing-function names where split acquire/release is the
    #: function's whole JOB (context-manager halves, lock wrappers)
    exempt_functions: tuple[str, ...] = ()
    common_escapes = True

    def acquire(self, stmt: ast.AST) -> Optional[tuple[str, str]]:
        """(variable, description) when ``stmt`` acquires, else None."""
        raise NotImplementedError

    def extra_release(self, stmt: ast.AST,
                      tracked: set[str]) -> set[str]:
        return set()


def _assigned_call(stmt: ast.AST) -> Optional[tuple[str, ast.Call]]:
    """(name, call) for ``x = call(...)`` / ``x = await call(...)``."""
    if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)):
        return None
    value = stmt.value
    if isinstance(value, ast.Await):
        value = value.value
    if isinstance(value, ast.Call):
        return stmt.targets[0].id, value
    return None


def _leaked_facts(cfg: CFG, spec: _ResourceSpec
                  ) -> Iterator[tuple[ast.AST, str, str, str]]:
    """(acquire stmt, var, description, path kind) for every
    acquisition that some path carries unreleased out of the function.
    May-analysis: a fact live at the normal or exceptional exit means
    at least one path leaks it."""
    acquires: list[tuple[int, str, str]] = []
    for idx, stmt in enumerate(cfg.stmts):
        if stmt is None or cfg.kinds[idx] != K_STMT:
            continue
        got = spec.acquire(stmt)
        if got is not None:
            acquires.append((idx, got[0], got[1]))
    if not acquires:
        return
    tracked = {var for _idx, var, _desc in acquires}
    facts = {(var, idx) for idx, var, _desc in acquires}
    gen = [frozenset()] * cfg.n_nodes
    kill = [frozenset()] * cfg.n_nodes
    for idx, stmt in enumerate(cfg.stmts):
        if stmt is None:
            continue
        dead = _rebound_names(stmt) & tracked
        if spec.common_escapes:
            dead |= _common_escapes(stmt) & tracked
        dead |= spec.extra_release(stmt, tracked)
        if dead:
            kill[idx] = frozenset(f for f in facts if f[0] in dead)
    for idx, var, _desc in acquires:
        gen[idx] = gen[idx] | {(var, idx)}
    inn = dataflow(cfg, gen, kill)
    at_exit = inn[cfg.exit] or frozenset()
    at_raise = inn[cfg.raise_exit] or frozenset()
    for idx, var, desc in acquires:
        fact = (var, idx)
        kinds = []
        if fact in at_exit:
            kinds.append("a normal path")
        if fact in at_raise:
            kinds.append("an exception/cancellation path")
        if kinds:
            yield cfg.stmts[idx], var, desc, " and ".join(kinds)


class _LeakRuleBase(Rule):
    """Shared check_project: run the spec's leak query over every
    function's CFG (memoized on the ProjectContext)."""

    project = True
    spec: _ResourceSpec

    def applies(self, rel: str) -> bool:
        return not rel.startswith("analysis/")

    def check(self, sf) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError("project rule: use check_project")

    def _message(self, var: str, desc: str, kind: str,
                 qualname: str) -> str:
        raise NotImplementedError

    def check_project(self, sfs, ctx) -> Iterator[tuple]:
        spec = self.spec
        for _key, info in sorted(ctx.graph.functions.items()):
            if info.rel.startswith("analysis/") \
                    or not isinstance(info.node, _FUNC_DEFS):
                continue
            if info.name in spec.exempt_functions:
                continue
            # cheap pre-scan: only build the CFG when something is
            # acquired in this function at all
            if not any(spec.acquire(s) is not None
                       for s in ast.walk(info.node)
                       if isinstance(s, ast.stmt)):
                continue
            cfg = ctx.cfg_of(info)
            for stmt, var, desc, kind in _leaked_facts(cfg, spec):
                yield (info.rel, stmt.lineno, stmt.col_offset,
                       self._message(var, desc, kind, info.qualname))


# ---- CB401: fd-leak ----

_FD_CHAINS = frozenset({
    "open", "io.open", "os.open", "os.fdopen", "mmap.mmap",
    "socket.socket", "socket.create_connection", "gzip.open",
    "bz2.open", "lzma.open", "tarfile.open",
})


class _FdSpec(_ResourceSpec):
    exempt_functions = ("close", "__exit__", "__aexit__", "__del__")

    def acquire(self, stmt):
        got = _assigned_call(stmt)
        if got is None:
            return None
        var, call = got
        chain = attr_chain(call.func)
        base, _, tail = chain.rpartition(".")
        if chain in _FD_CHAINS or (tail == "open" and "fsio" in base):
            return var, f"{chain}()"
        return None

    def extra_release(self, stmt, tracked):
        out: set[str] = set()
        for node in _exprs_under(stmt):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "close"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in tracked):
                out.add(node.func.value.id)
        return out


class FdLeakRule(_LeakRuleBase):
    """CB401 — acquired handles must reach a release on all CFG paths.

    The PR 10 cancellation leak was exactly this shape: an opener's
    handle orphaned on a path the author never drew — ``to_thread``'s
    await cancelled mid-open.  RAII makes that impossible in the Rust
    reference; here the CFG makes it checkable: every ``x = open(...)``
    (or ``os.open``/``fdopen``, ``mmap.mmap``, ``socket.socket``, an
    fsio-seam ``open``) starts a fact the dataflow must see released on
    EVERY path to either exit — normal fall-through, ``return``,
    ``raise``, and the exc edges every call and every ``await``
    (cancellation point) carry.  Releases: ``x.close()``, returning or
    yielding x, storing x into an attribute/container, passing x to a
    callee (custody transfer — ``aio.open_in_thread``'s closer is the
    async shape), a ``with`` block.  Fix pattern: ``with open(...)``
    when the scope is local; the ``try/except BaseException: close;
    raise`` opener guard when the handle outlives the opener (the
    ``FileReader._ensure`` shape); ``# lint: fd-leak-ok <reason>`` for
    deliberate hand-off schemes the dataflow cannot see.
    """

    id = "CB401"
    slug = "fd-leak"
    description = ("acquired file/socket/mmap handles must be released "
                   "or custody-transferred on every CFG path")
    spec = _FdSpec()

    def _message(self, var, desc, kind, qualname):
        return (f"{var} = {desc} in {qualname}() leaks on {kind}: no "
                "close()/custody transfer reaches the scope exit — use "
                "`with`, the opener try/except-BaseException guard, or "
                "aio.open_in_thread custody; justify with "
                "`# lint: fd-leak-ok <reason>`")


# ---- CB402: lock-discipline ----

_LOCK_ACQ_FLAGS = ("LOCK_EX", "LOCK_SH")


def _flock_key(call: ast.Call) -> Optional[str]:
    if attr_chain(call.func).rsplit(".", 1)[-1] != "flock" \
            or len(call.args) < 2:
        return None
    fd = attr_chain(call.args[0]) or "<fd>"
    return f"flock({fd})"


def _flock_flags(call: ast.Call) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(call.args[1]):
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Name):
            out.add(node.id)
    return out


class _LockSpec(_ResourceSpec):
    # a context-manager half or lock wrapper IS split acquire/release
    exempt_functions = ("__enter__", "__exit__", "__aenter__",
                       "__aexit__", "acquire", "release", "locked")
    common_escapes = False  # a stored lock still needs its release

    def acquire(self, stmt):
        for node in _exprs_under(stmt):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                chain = attr_chain(node.func.value)
                if chain:
                    return chain, f"{chain}.acquire()"
            key = _flock_key(node)
            if key is not None:
                flags = _flock_flags(node)
                if flags & set(_LOCK_ACQ_FLAGS):
                    return key, f"{key} exclusive/shared"
        return None

    def extra_release(self, stmt, tracked):
        out: set[str] = set()
        for node in _exprs_under(stmt):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                chain = attr_chain(node.func.value)
                if chain in tracked:
                    out.add(chain)
            key = _flock_key(node)
            if key in tracked and "LOCK_UN" in _flock_flags(node):
                out.add(key)
        return out


class LockDisciplineRule(_LeakRuleBase):
    """CB402 — every acquire pairs with a release on every path.

    A lock held across an unplanned exit is worse than a leaked fd: the
    next acquirer deadlocks, and on this box's single-core runtime a
    wedged flock on ``<root>/.lock`` stops every cross-process slab
    append at once.  The CFG check is the same must-pair query as
    CB401 with ``acquire()``/``release()`` (and ``flock(fd, LOCK_EX)``
    / ``flock(fd, LOCK_UN)``) as the gen/kill pair — custody transfer
    deliberately does NOT release a lock (storing it somewhere is not
    unlocking it).  Preferred fix: ``with lock:`` — the interpreter
    then proves the pairing structurally and this rule never fires; a
    split pair that must stay split (context-manager halves are
    exempted by name) records why with ``# lint: lock-discipline-ok
    <reason>``.
    """

    id = "CB402"
    slug = "lock-discipline"
    description = ("lock/flock acquires must pair with a release on "
                   "every CFG path (prefer `with lock:`)")
    spec = _LockSpec()

    def _message(self, var, desc, kind, qualname):
        return (f"{desc} in {qualname}() is not released on {kind} — "
                "the next acquirer deadlocks; prefer `with lock:` (the "
                "interpreter proves the pairing), else release in a "
                "finally, or justify with "
                "`# lint: lock-discipline-ok <reason>`")


# ---- CB403: task-custody ----

_TASK_TAILS = ("create_task", "ensure_future")


class _TaskSpec(_ResourceSpec):
    def acquire(self, stmt):
        got = _assigned_call(stmt)
        if got is None:
            return None
        var, call = got
        tail = attr_chain(call.func).rsplit(".", 1)[-1]
        if tail in _TASK_TAILS:
            return var, f"{tail}()"
        return None

    def extra_release(self, stmt, tracked):
        out: set[str] = set()
        for node in _exprs_under(stmt):
            if isinstance(node, ast.Await):
                # awaiting anything that mentions the task observes it
                # (await t, await shield(t), await gather(*, t))
                out |= _names_under(node.value) & tracked
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_done_callback"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in tracked):
                # the sanctioned done-callback ownership (CB203's
                # custody convention)
                out.add(node.func.value.id)
        return out


class TaskCustodyRule(_LeakRuleBase):
    """CB403 — created tasks keep an owner on every path out of the
    creating scope (the CFG-precise upgrade of the syntactic CB203).

    CB203 flags ``create_task(...)`` whose result is dropped on the
    spot; it cannot see the assigned-then-leaked shape — ``t =
    create_task(...)`` followed by an early return, a raise, or a
    cancellation delivered at an intervening await, with ``t`` never
    stored, awaited, or reaped.  The PR 16 unreaped reader tasks died
    exactly there.  Custody = awaiting something that mentions the task
    (``await t``, ``await shield(t)``, ``gather``), storing it
    (attribute/container/alias), returning/yielding it, passing it to
    a callee, or ``add_done_callback`` (the done-callback ownership
    CB203 already sanctions).  ``t.cancel()`` alone is NOT custody —
    cancellation is only requested until an await observes it (CB303's
    point, made path-sensitive here).  Suppress deliberate
    fire-and-forget with ``# lint: task-custody-ok <reason>``.
    """

    id = "CB403"
    slug = "task-custody"
    description = ("assigned tasks must be stored/awaited/reaped on "
                   "every CFG path out of the creating scope")
    spec = _TaskSpec()

    def _message(self, var, desc, kind, qualname):
        return (f"{var} = {desc} in {qualname}() loses its owner on "
                f"{kind}: the task is never stored, awaited, or "
                "cancelled-and-awaited there — it outlives the scope "
                "unobserved (leak under SANITIZE, exceptions vanish); "
                "await/gather it, store it, or justify with "
                "`# lint: task-custody-ok <reason>`")


# ---- CB404: unbounded-deadline ----

#: where requests, dispatches, and the scrub walk enter the system —
#: the frames a deadline must exist *somewhere* below
DEADLINE_ROOTS = (
    ("gateway/http.py", "*"),
    ("gateway/workers.py", "*"),
    ("ops/dispatch_pipeline.py", "*"),
    ("cluster/scrub.py", "ScrubDaemon.run"),
)

#: CB101 already polices these by path (with its own suppressions);
#: flagging there again would demand a second marker per site
_DEADLINE_GOVERNED = UnboundedAwaitRule.paths + ("analysis/", "sim/")

#: call wrappers that impose a deadline on everything beneath them
_BOUNDING_TAILS = ("wait_for", "run_bounded_dispatch")


class UnboundedDeadlineRule(Rule):
    """CB404 — every await reachable from a serving/dispatch/scrub root
    is bounded at SOME frame (the interprocedural lift of CB101).

    CB101 proves "degrade, never hang" per module, on a path list —
    which leaves two gaps this rule closes over the call graph.  Gap
    one: a bare await in ``file/location.py`` or ``cluster/cluster.py``
    (off CB101's list) hangs a gateway GET exactly as hard as one in
    ``gateway/``.  Gap two, the converse: a deadline does not have to
    sit AT the await — ``asyncio.wait_for(self._fetch(), t)`` bounds
    every await inside ``_fetch`` and everything it calls.  So the
    traversal starts at the roots (gateway handlers, the worker
    supervisor, the dispatch pipeline, the scrub walk) and refuses to
    cross a call edge whose every recorded call site sits inside a
    bounding wrapper (``wait_for``/``run_bounded_dispatch``): what it
    still reaches is provably deadline-free on some root path, and a
    CB101-shaped await there (bare future/task, ``.wait()``/
    ``.join()``-family) is a real whole-program hang.  Modules CB101
    already governs are excluded — one rule, one marker per site.
    Fix: bound at the site or at the narrowest caller that owns the
    deadline budget; justify liveness-by-construction with
    ``# lint: unbounded-deadline-ok <reason>``.
    """

    id = "CB404"
    slug = "unbounded-deadline"
    description = ("awaits reachable from serving/dispatch/scrub roots "
                   "must be bounded at some frame")
    project = True

    def applies(self, rel: str) -> bool:
        return not rel.startswith(_DEADLINE_GOVERNED)

    def check(self, sf) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError("project rule: use check_project")

    @staticmethod
    def _bounded_site(call: ast.Call, parents: dict) -> bool:
        """True when ``call`` sits inside the argument subtree of a
        bounding wrapper in its own function."""
        cur = parents.get(call)
        while cur is not None and not isinstance(
                cur, _FUNC_DEFS + (ast.Lambda,)):
            if isinstance(cur, ast.Call):
                tail = attr_chain(cur.func).rsplit(".", 1)[-1]
                if tail in _BOUNDING_TAILS:
                    return True
            cur = parents.get(cur)
        return False

    def _unbounded_reachable(self, ctx, roots) -> list:
        """Closure from the roots traversing only call edges with at
        least one deadline-free route (an edge is skipped when every
        recorded call site is inside a bounding wrapper; handoffs with
        no recorded site — spawned tasks — are never bounded)."""
        graph = ctx.graph
        parents_by_rel: dict[str, dict] = {}
        sites_by_edge: dict[tuple, list[ast.Call]] = {}
        for callee, pairs in graph.call_sites.items():
            for caller, call in pairs:
                sites_by_edge.setdefault((caller, callee),
                                         []).append(call)
        seen = set()
        stack = [k for k in roots if k in graph.functions]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            ctx.note_summary(("deadline", key))
            for callee in graph.edges.get(key, ()):
                if callee in seen:
                    continue
                sites = sites_by_edge.get((key, callee), ())
                if sites:
                    rel = key[0]
                    if rel not in parents_by_rel:
                        sf = ctx.by_rel.get(rel)
                        parents_by_rel[rel] = \
                            _parents(sf.tree) if sf else {}
                    if all(self._bounded_site(c, parents_by_rel[rel])
                           for c in sites):
                        continue  # bounded at every frame that calls it
                stack.append(callee)
        return [ctx.graph.functions[k] for k in seen]

    def check_project(self, sfs, ctx) -> Iterator[tuple]:
        roots = ctx.resolve_roots(DEADLINE_ROOTS)
        if not roots:
            return
        infos = self._unbounded_reachable(ctx, roots)
        infos.sort(key=lambda i: (i.rel, i.lineno, i.qualname))
        for info in infos:
            if info.rel.startswith(_DEADLINE_GOVERNED) \
                    or not isinstance(info.node, ast.AsyncFunctionDef):
                continue
            for node in iter_body_nodes(info.node):
                if not isinstance(node, ast.Await):
                    continue
                value = node.value
                shape = None
                if isinstance(value, (ast.Name, ast.Attribute)):
                    shape = "a bare future/task"
                elif (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Attribute)
                        and value.func.attr
                        in UnboundedAwaitRule.WATCH):
                    shape = f".{value.func.attr}()"
                if shape is None:
                    continue
                yield (info.rel, node.lineno, node.col_offset,
                       f"await on {shape} in {info.qualname}() is "
                       "reachable from the serving/dispatch/scrub "
                       "roots with no deadline at ANY frame — a dead "
                       "peer or parked device hangs the whole request "
                       "('degrade, never hang'); bound it with "
                       "asyncio.wait_for here or at the caller that "
                       "owns the budget, or justify with "
                       "`# lint: unbounded-deadline-ok <reason>`")


# ---- CB405: metered-io ----

#: where scrub/repair I/O enters: the daemon walk and the planner's
#: per-part entry (both construct/carry the TokenBucket)
METER_ROOTS = (
    ("cluster/scrub.py", "ScrubDaemon.run"),
    ("cluster/repair.py", "repair_part"),
)

#: the metering domain: the modules that OWN the byte budget.  The
#: shared read machinery below them (file/location.py et al.) serves
#: unmetered foreground traffic too — the contract is that scrub and
#: repair charge before they call into it.
_METER_SCOPE = ("cluster/scrub.py", "cluster/repair.py")

_METER_FACT = "metered"


def _take_in_stmt(stmt: ast.AST) -> bool:
    for node in _exprs_under(stmt):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "take"
                and "bucket" in attr_chain(node.func.value).lower()):
            return True
    return False


def _io_calls(stmt: ast.AST) -> list[ast.Call]:
    """Chunk-byte I/O calls in this statement: ``.read()``/``.write()``
    on anything but the metadata plane (control plane, not chunk I/O)."""
    out: list[ast.Call] = []
    for node in _exprs_under(stmt):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("read", "write")
                and "metadata" not in attr_chain(node.func).lower()):
            out.append(node)
    return out


class MeteredIoRule(Rule):
    """CB405 — scrub/repair chunk I/O charges the TokenBucket first.

    ``tunables.scrub_bytes_per_sec`` exists to protect foreground
    traffic; the contract (charged into BASELINE by configs 11/13) is
    *exact* metering — every repair byte charges the budget, charged
    BEFORE the I/O so a burst cannot land and then apologize.  This
    rule proves it with a must-dominance query over the CFGs of every
    ``cluster/scrub.py``/``cluster/repair.py`` function reachable from
    the scrub/repair roots: a ``.read()``/``.write()`` chunk I/O call
    must have a ``bucket.take()`` on EVERY path from the function
    entry, and each charge covers exactly one I/O (the metered fact is
    killed at the I/O, so take-once-read-twice re-flags).  Per-function
    summaries compose through the call graph to fixpoint: a helper
    whose every in-scope call site is itself dominated by a charge is
    *entered metered*, so charge-in-the-caller patterns (``_localize``
    → ``_read_full``) prove through.  Metadata reads/writes are exempt
    by receiver — the ref round-trip is the control plane.  Deliberate
    unmetered I/O (none today) records why with
    ``# lint: metered-io-ok <reason>``.
    """

    id = "CB405"
    slug = "metered-io"
    description = ("scrub/repair-reachable chunk reads/writes must be "
                   "dominated by a TokenBucket charge")
    project = True
    paths = _METER_SCOPE

    def check(self, sf) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError("project rule: use check_project")

    def check_project(self, sfs, ctx) -> Iterator[tuple]:
        roots = ctx.resolve_roots(METER_ROOTS)
        if not roots:
            return
        graph = ctx.graph
        infos = {info.key: info
                 for info in ctx.reachable_infos(roots)
                 if info.rel.startswith(_METER_SCOPE)
                 and isinstance(info.node, _FUNC_DEFS)}
        cfgs = {}
        gens = {}
        kills = {}
        io_nodes: dict[tuple, list[tuple[int, ast.Call]]] = {}
        call_stmt: dict[tuple, dict[int, int]] = {}
        for key, info in infos.items():
            cfg = ctx.cfg_of(info)
            ctx.note_summary(("meter", key))
            cfgs[key] = cfg
            gen = [frozenset()] * cfg.n_nodes
            kill = [frozenset()] * cfg.n_nodes
            sites: list[tuple[int, ast.Call]] = []
            stmt_of: dict[int, int] = {}
            for idx, stmt in enumerate(cfg.stmts):
                if stmt is None:
                    continue
                if _take_in_stmt(stmt):
                    gen[idx] = frozenset({_METER_FACT})
                calls = _io_calls(stmt)
                if calls:
                    # one charge covers one I/O: consume the fact
                    kill[idx] = frozenset({_METER_FACT})
                    for call in calls:
                        sites.append((idx, call))
                for node in _exprs_under(stmt):
                    if isinstance(node, ast.Call):
                        stmt_of[id(node)] = idx
            gens[key], kills[key] = gen, kill
            io_nodes[key] = sites
            call_stmt[key] = stmt_of
        # fixpoint: entered-metered flows caller -> callee through
        # call sites that are themselves must-metered
        entered = {key: False for key in infos}
        inns = {}
        for _round in range(len(infos) + 1):
            for key in infos:
                init = frozenset({_METER_FACT}) if entered[key] \
                    else frozenset()
                inns[key] = dataflow(cfgs[key], gens[key], kills[key],
                                     must=True, init=init)
            changed = False
            for key in infos:
                pairs = [(ck, call) for ck, call
                         in graph.call_sites.get(key, ())
                         if ck in infos]
                if not pairs or entered[key]:
                    continue
                ok = True
                for ck, call in pairs:
                    sidx = call_stmt[ck].get(id(call))
                    state = inns[ck][sidx] if sidx is not None else None
                    if state is None or _METER_FACT not in state:
                        ok = False
                        break
                if ok:
                    entered[key] = True
                    changed = True
            if not changed:
                break
        for key in sorted(infos):
            info = infos[key]
            inn = inns[key]
            for idx, call in io_nodes[key]:
                state = inn[idx]
                if state is not None and _METER_FACT in state:
                    continue
                tail = call.func.attr
                yield (info.rel, call.lineno, call.col_offset,
                       f".{tail}() in {info.qualname}() is reachable "
                       "from the scrub/repair roots but not dominated "
                       "by a bucket.take() charge — unmetered repair "
                       "I/O saturates the disks the byte-rate bound "
                       "exists to protect; charge the TokenBucket "
                       "before the I/O (every path, one charge per "
                       "I/O) or justify with "
                       "`# lint: metered-io-ok <reason>`")


LIFETIME_RULES: tuple[Rule, ...] = (
    FdLeakRule(),
    LockDisciplineRule(),
    TaskCustodyRule(),
    UnboundedDeadlineRule(),
    MeteredIoRule(),
)
