"""Shared per-run project context: one call graph, memoized reachability.

``core.run_analysis`` constructs ONE :class:`ProjectContext` per run and
hands it to every project-granular rule (CB204, the CB3xx family), so
the interprocedural pass parses and links the tree exactly once however
many rules consume it — the property that keeps ``scripts/check.sh``
inside its runtime budget with the tunnel down.

Root *specs* name functions structurally rather than by line number so
the rules survive refactors: ``("file/slab.py", "SlabStore.append")``
matches the method wherever it moves inside the file, and a spec whose
qualname is ``"*"`` roots every function in the module (the sim-scenario
roots).  Specs that match nothing are reported by
:meth:`ProjectContext.resolve_roots` callers as rule errors rather than
silently shrinking the reachable set.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

from .callgraph import CallGraph, FuncInfo, build_call_graph


class ProjectContext:
    """Lazily built call graph + cached reachability over one scan."""

    def __init__(self, sources: Sequence) -> None:
        self._sources = list(sources)
        self._graph: Optional[CallGraph] = None
        self._reach_cache: dict[frozenset, frozenset] = {}
        #: function key -> built CFG (the CB4xx rules share one graph
        #: per function however many rules query it)
        self._cfg_cache: dict = {}
        #: interprocedural summary tags recorded by dataflow rules —
        #: counted into ``--graph-stats``
        self._summaries: set = set()
        #: rel -> SourceFile, for rules that need suppression scans
        self.by_rel = {sf.rel: sf for sf in self._sources}

    @property
    def graph(self) -> CallGraph:
        if self._graph is None:
            self._graph = build_call_graph(self._sources)
        return self._graph

    def resolve_roots(self, specs: Iterable[tuple[str, str]]
                      ) -> set[tuple[str, str]]:
        """Graph keys for root specs.

        A spec is ``(rel, qualname_suffix)``: it matches functions in
        ``rel`` whose qualname equals the suffix OR ends with
        ``"." + suffix`` (so ``"write"`` matches every ``write`` method
        in the module without naming each class).  ``("sim/x.py", "*")``
        roots the whole module."""
        graph = self.graph
        keys: set[tuple[str, str]] = set()
        for rel, suffix in specs:
            for info in graph.functions.values():
                if info.rel != rel:
                    continue
                if suffix == "*" or info.qualname == suffix \
                        or info.qualname.endswith("." + suffix):
                    keys.add(info.key)
        return keys

    def reachable_from(self, roots: Iterable[tuple[str, str]]
                       ) -> frozenset:
        """Memoized transitive closure over the call graph."""
        key = frozenset(roots)
        cached = self._reach_cache.get(key)
        if cached is None:
            cached = frozenset(self.graph.reachable(key))
            self._reach_cache[key] = cached
        return cached

    def cfg_of(self, info: FuncInfo):
        """Memoized statement-granular CFG for one function (built by
        ``analysis.cfg.build_cfg``; shared across every CB4xx rule)."""
        cfg = self._cfg_cache.get(info.key)
        if cfg is None:
            from .cfg import build_cfg
            cfg = build_cfg(info.node)
            self._cfg_cache[info.key] = cfg
        return cfg

    def note_summary(self, tag) -> None:
        """Record one composed per-function dataflow summary (an opaque
        hashable tag) for the ``--graph-stats`` report."""
        self._summaries.add(tag)

    def cfg_stats(self) -> dict[str, int]:
        """CFG-layer totals for ``--graph-stats`` (zeroes until a CB4xx
        rule has run and populated the caches)."""
        cfgs = self._cfg_cache.values()
        return {
            "cfg_functions": len(self._cfg_cache),
            "cfg_blocks": sum(c.n_nodes for c in cfgs),
            "cfg_edges": sum(c.n_edges for c in cfgs),
            "dataflow_summaries": len(self._summaries),
        }

    def reachable_infos(self, roots: Iterable[tuple[str, str]]
                        ) -> list[FuncInfo]:
        """FuncInfos for the closure, in deterministic (rel, line)
        order so findings sort stably across runs."""
        graph = self.graph
        infos = [graph.functions[k]
                 for k in self.reachable_from(roots)
                 if k in graph.functions]
        infos.sort(key=lambda i: (i.rel, i.lineno, i.qualname))
        return infos
