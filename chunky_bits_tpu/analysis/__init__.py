"""Project-native invariant linter.

The invariants that keep this system correct on a flaky-tunnel TPU box
live in CLAUDE.md prose; this package makes them machine-checked.  Pure
stdlib ``ast``/``tokenize`` — importing it never pulls jax, numpy, or
aiohttp, so the gate runs even when the device tunnel is down and in
bare CI runners.

Invariant -> rule (suppression slug in backticks — the exact token the
``# lint: <slug>-ok <reason>`` marker takes; each rule's docstring
carries the full story):

- degrade-never-hang (bounded device/network waits) -> CB101
  ``unbounded-await``
- env flags baked into jit caches at first dispatch   -> CB102
  ``env-read``
- 1-core box, workers parked in PJRT block exit       -> CB103
  ``thread``
- degraded-mode fallbacks must not eat corruption     -> CB104
  ``broad-except``
- this XLA CPU backend's jit-body pathologies         -> CB105
  ``jit-hygiene``
- strict typing on the public compute/serve surfaces  -> CB106
  ``annotations``

CB2xx — concurrency hazards of the two-plane host/async runtime
(concurrency.py; ``--select CB2`` runs the family alone):

- the event loop must never execute blocking sync I/O -> CB201
  ``async-blocking``
- threading locks must not be held across awaits      -> CB202
  ``lock-across-await``
- every spawned task needs an owner                   -> CB203
  ``task-leak``
- worker code re-enters the loop only through the
  _threadsafe doors (call-graph pass, callgraph.py)   -> CB204
  ``cross-plane``
- serve-path singletons are per-event-loop            -> CB205
  ``loop-shared``

CB3xx — whole-program reachability (flow.py over the shared
function-granular call graph in callgraph.py + reachability.py;
``--select CB3`` runs the family alone; ``--explain CB3xx`` prints any
rule's full rationale, ``--graph-stats`` reports graph precision):

- crash harness replays only seam-recorded mutations:
  no durability op off-seam anywhere a durability root
  (slab append/compact, publish, metadata write,
  repair rewrite) can reach                           -> CB301
  ``fsio-escape``
- same seed => byte-identical trace: no wall-clock
  read anywhere a sim scenario can reach              -> CB302
  ``clock-escape``
- cancellation must propagate (never swallowed),
  complete (cancel() is awaited), and never strand a
  write->replace publish window                       -> CB303
  ``cancel-safety``
- production planes import NOTHING from sim/ — proven
  statically incl. lazy in-function imports (the
  runtime subprocess pin in tests/test_sim.py covers
  the default import closure; both stay)              -> CB304
  ``sim-purity``
- closed-set metric labels hold at the CALL SITES of
  functions that feed parameters into ``.labels()``   -> CB305
  ``label-flow``

CB4xx — resource lifetime & deadline propagation (lifetime.py over
statement-granular CFGs from cfg.py: explicit exception/finally/
with-unwind edges plus await-as-cancellation-point edges, a worklist
may/must dataflow engine, per-function summaries composed through the
shared call graph; ``--select CB4`` runs the family alone):

- leak-strict extends to EVERY path out of a function:
  an acquired fd/socket/mmap is closed, returned,
  stored, or handed off even when a statement between
  acquire and release raises or is cancelled          -> CB401
  ``fd-leak``
- a manual lock/flock acquire reaches its release on
  all paths (an exception between them deadlocks
  every later taker)                                  -> CB402
  ``lock-discipline``
- CFG-precise task custody: an ASSIGNED task can
  still lose its owner when the path between spawn
  and await raises; cancel() alone observes nothing   -> CB403
  ``task-custody``
- degrade-never-hang, interprocedurally: serving-
  plane paths into modules off CB101's list still
  need a deadline at SOME frame (wait_for at the
  call site bounds everything beneath)                -> CB404
  ``unbounded-deadline``
- scrub/repair I/O is exactly metered: every read/
  write dominated by its own bucket.take() charge,
  caller-side charges compose through summaries       -> CB405
  ``metered-io``

The runtime side of the same contract lives in ``sanitizer.py``: an
opt-in (``$CHUNKY_BITS_TPU_SANITIZE``) loop-stall watchdog, task-leak
registry, and HostPipeline handoff checker.  It is deliberately NOT
imported here — the off path must never load instrumentation (and this
package must keep importing clean on a bare interpreter).

Entry points: ``python -m chunky_bits_tpu.analysis`` and
``scripts/check.sh`` (tier-1 and CI both run the latter).  Violations
are suppressed inline with ``# lint: <slug>-ok <reason>`` (the reason is
mandatory) or recorded in ``analysis/baseline.toml`` so pre-existing
findings stay green while NEW violations fail the gate.
"""

from chunky_bits_tpu.analysis.core import (  # noqa: F401
    Violation,
    load_baseline,
    run_analysis,
    write_baseline,
)
from chunky_bits_tpu.analysis.rules import ALL_RULES  # noqa: F401
