"""Statement-granular control-flow graphs + forward dataflow (CB4xx).

The CB1xx-CB3xx families reason over raw ASTs and a call graph with no
notion of control flow, so "released on ALL paths, including the
exception and cancellation paths" — the exact shape of the PR 10
``to_thread(open)`` orphaned-fd leak and the PR 16 unreaped reader
tasks, and of the "degrade, never hang" invariant — was the one class
of CLAUDE.md invariant the linter could not machine-check.  This module
is the missing compiler layer: intra-function CFGs over stdlib ``ast``
alone (tunnel-down-safe like the rest of ``chunky_bits_tpu/analysis/``)
plus a small forward must/may dataflow engine the CB4xx rules
(``analysis/lifetime.py``) instantiate with rule-specific gen/kill
sets.

Graph shape
-----------

One node per *statement* (plus synthetic entry/exit/raise-exit and
per-``try`` dispatch/finally-pad nodes).  Edges come in two kinds:

- **flow** — ordinary sequencing, branching, loop back edges;
- **exc**  — a statement that may raise transfers control to the
  innermost handler frame (its ``try``'s except-dispatch node, else the
  enclosing ``finally``, else the function's exceptional exit).  A
  statement "may raise" when its own subtree (nested ``def``/``lambda``
  bodies excluded) contains a call, an ``await``, a ``raise``/
  ``assert``, or is a loop/``with`` header (``__iter__``/``__enter__``
  can raise).  *Every await is a cancellation point* — ``await``,
  ``async for`` and ``async with`` may raise ``CancelledError`` at any
  suspension, so they always carry an exc edge; that is the
  await-as-cancellation-point edge the resource-lifetime rules lean on.

Deliberate simplifications (all err toward MORE paths, the safe
direction for leak detection — a may-analysis over a superset of real
paths can only over-flag, never under-flag, and the shared
``# lint: <slug>-ok`` machinery absorbs the rare excess):

- ``finally`` bodies are built once and their exits fan out to every
  continuation the block could resume (fall-through AND exception
  propagation), rather than being duplicated per continuation kind.
- ``return``/``break``/``continue`` under a ``try/finally`` edge both
  to the finally pad and directly to their target.
- exc edges transfer the statement's *pre*-state with kills applied
  but gens withheld: an acquisition that raises acquired nothing, while
  a release interrupted mid-call is still treated as released (closing
  a handle that errored while closing is not a leak worth a finding).
- ``with`` blocks assume the context manager does not suppress
  exceptions (none of ours do); the unwind itself is the body
  statements' exc edges — ``__exit__`` runs on every one of them.

Dataflow engine
---------------

:func:`dataflow` runs a forward gen/kill analysis to fixpoint over a
CFG: *may* (union meet — "does any path carry the fact here", the leak
query) or *must* (intersection meet — "do all paths carry it", the
dominance query CB405 uses for charge-before-I/O).  Facts are opaque
hashables; per-edge transfer implements the pre-state convention above.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Sequence

#: node kinds (``CFG.kinds``); synthetic nodes carry no statement
K_ENTRY = "entry"
K_EXIT = "exit"
K_RAISE = "raise-exit"
K_STMT = "stmt"
K_DISPATCH = "except-dispatch"
K_FINPAD = "finally-pad"
K_HANDLER = "handler"

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: statement types that are may-raise by construction, before looking
#: for calls/awaits inside them
_RAISING_STMTS = (ast.Raise, ast.Assert, ast.With, ast.AsyncWith,
                  ast.For, ast.AsyncFor)


def stmt_expressions(stmt: ast.AST) -> list[ast.AST]:
    """The expressions evaluated AT this statement's CFG node.

    Compound statements get one node for their *header* only — the body
    statements have nodes of their own — so analyses must not credit a
    body's calls/releases to the header (or an ``except`` body's to its
    handler node, whose AST children include it).  Simple statements
    evaluate their whole subtree."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target, stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return list(stmt.items)
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, ast.ExceptHandler):
        return [stmt.type] if stmt.type is not None else []
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []  # a nested definition's code runs when called
    return [stmt]


def _header_subtrees(stmt: ast.AST) -> Iterator[ast.AST]:
    """Walk the node's header expressions, stopping at nested
    def/lambda boundaries (their code runs when THEY are called)."""
    stack = list(stmt_expressions(stmt))
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _FUNC_NODES):
                continue
            stack.append(child)


#: Method tails that return without raising: ``Task.cancel()`` /
#: ``Handle.cancel()`` only *request* cancellation (bool/None result,
#: no exception path).  Treating the request call as raising would turn
#: every ``finally: t.cancel(); await t`` reaper — the canonical owned
#: shape — into a false exception-path leak between the two statements.
_NONRAISING_TAILS = frozenset({"cancel"})


def _never_raises(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr in _NONRAISING_TAILS)


def may_raise(stmt: ast.AST) -> bool:
    """Conservative "can this statement transfer control to a handler":
    any call or suspension point evaluated at this node, or a statement
    whose protocol methods can raise (see module docstring)."""
    if isinstance(stmt, _RAISING_STMTS):
        return True
    for node in _header_subtrees(stmt):
        if isinstance(node, ast.Await):
            return True
        if isinstance(node, ast.Call) and not _never_raises(node):
            return True
    return False


def is_cancellation_point(stmt: ast.AST) -> bool:
    """True when the statement suspends at this node (await in a header
    expression / async-for / async-with) — a ``CancelledError`` can
    surface here even if nothing else in the statement can fail."""
    if isinstance(stmt, (ast.AsyncFor, ast.AsyncWith)):
        return True
    for node in _header_subtrees(stmt):
        if isinstance(node, ast.Await):
            return True
    return False


class CFG:
    """One function's control-flow graph.  Nodes are indices into the
    parallel ``stmts``/``kinds`` lists; ``flow``/``exc`` hold successor
    sets per node (see module docstring for edge semantics)."""

    def __init__(self) -> None:
        self.stmts: list[Optional[ast.AST]] = []
        self.kinds: list[str] = []
        self.flow: list[set[int]] = []
        self.exc: list[set[int]] = []
        self.entry = self.add_node(K_ENTRY)
        self.exit = self.add_node(K_EXIT)
        self.raise_exit = self.add_node(K_RAISE)

    def add_node(self, kind: str,
                 stmt: Optional[ast.AST] = None) -> int:
        self.stmts.append(stmt)
        self.kinds.append(kind)
        self.flow.append(set())
        self.exc.append(set())
        return len(self.stmts) - 1

    @property
    def n_nodes(self) -> int:
        return len(self.stmts)

    @property
    def n_edges(self) -> int:
        return sum(len(s) for s in self.flow) \
            + sum(len(s) for s in self.exc)

    def node_of(self, stmt: ast.AST) -> Optional[int]:
        """Index of the node carrying ``stmt``, if any."""
        for idx, s in enumerate(self.stmts):
            if s is stmt:
                return idx
        return None

    def preds(self) -> list[list[tuple[int, bool]]]:
        """Per-node predecessor list as ``(pred, is_exc)`` pairs."""
        out: list[list[tuple[int, bool]]] = [[] for _ in self.stmts]
        for src, succs in enumerate(self.flow):
            for dst in succs:
                out[dst].append((src, False))
        for src, succs in enumerate(self.exc):
            for dst in succs:
                out[dst].append((src, True))
        return out


def _catches_everything(handler: ast.AST) -> bool:
    """True for ``except:`` and ``except BaseException`` — the only
    clauses that also catch ``CancelledError`` (``except Exception``
    does not, since 3.8)."""
    if handler.type is None:
        return True
    names = []
    if isinstance(handler.type, ast.Tuple):
        names = [t for t in handler.type.elts]
    else:
        names = [handler.type]
    for t in names:
        tail = t.attr if isinstance(t, ast.Attribute) else \
            t.id if isinstance(t, ast.Name) else ""
        if tail == "BaseException":
            return True
    return False


class _Builder:
    """Single-pass recursive CFG construction.  ``cursor`` threading:
    each statement builder takes the list of dangling node indices
    whose fall-through reaches it, and returns the new dangling set."""

    def __init__(self) -> None:
        self.cfg = CFG()
        #: innermost-last exception targets (dispatch/finpad nodes);
        #: empty = propagate to the function's exceptional exit
        self.exc_stack: list[int] = []
        #: active finally pads a non-local exit must run through
        self.fin_stack: list[int] = []
        #: (header node, break-exit collector, fin_stack depth at entry)
        self.loop_stack: list[tuple[int, list[int], int]] = []

    # -- plumbing --

    def _exc_target(self) -> int:
        return self.exc_stack[-1] if self.exc_stack \
            else self.cfg.raise_exit

    def _wire(self, frm: Sequence[int], to: int) -> None:
        for f in frm:
            self.cfg.flow[f].add(to)

    def _new(self, stmt: Optional[ast.AST], cursor: Sequence[int],
             kind: str = K_STMT) -> int:
        n = self.cfg.add_node(kind, stmt)
        self._wire(cursor, n)
        if stmt is not None and may_raise(stmt):
            self.cfg.exc[n].add(self._exc_target())
        return n

    def _nonlocal_exit(self, n: int, target: int,
                       fin_floor: int = 0) -> None:
        """Wire a return/break/continue node: directly to its target
        AND through any finally pads entered above ``fin_floor`` (both
        edges — see the simplifications note)."""
        self.cfg.flow[n].add(target)
        if len(self.fin_stack) > fin_floor:
            self.cfg.flow[n].add(self.fin_stack[-1])

    # -- statement dispatch --

    def seq(self, stmts: Sequence[ast.AST],
            cursor: list[int]) -> list[int]:
        for stmt in stmts:
            cursor = self.build_stmt(stmt, cursor)
        return cursor

    def build_stmt(self, stmt: ast.AST,
                   cursor: list[int]) -> list[int]:
        if isinstance(stmt, ast.Return):
            n = self._new(stmt, cursor)
            self._nonlocal_exit(n, self.cfg.exit)
            return []
        if isinstance(stmt, ast.Raise):
            n = self.cfg.add_node(K_STMT, stmt)
            self._wire(cursor, n)
            self.cfg.exc[n].add(self._exc_target())
            return []
        if isinstance(stmt, ast.Break):
            n = self._new(stmt, cursor)
            if self.loop_stack:
                _header, breaks, fin_floor = self.loop_stack[-1]
                breaks.append(n)
                if len(self.fin_stack) > fin_floor:
                    self.cfg.flow[n].add(self.fin_stack[-1])
            return []
        if isinstance(stmt, ast.Continue):
            n = self._new(stmt, cursor)
            if self.loop_stack:
                header, _breaks, fin_floor = self.loop_stack[-1]
                self._nonlocal_exit(n, header, fin_floor)
            return []
        if isinstance(stmt, ast.If):
            return self._build_if(stmt, cursor)
        if isinstance(stmt, (ast.While,)):
            return self._build_while(stmt, cursor)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._build_for(stmt, cursor)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            n = self._new(stmt, cursor)
            return self.seq(stmt.body, [n])
        if isinstance(stmt, ast.Try):
            return self._build_try(stmt, cursor)
        if isinstance(stmt, ast.Match):
            return self._build_match(stmt, cursor)
        # simple statement (incl. nested def/class definitions, whose
        # bodies are separate graphs)
        return [self._new(stmt, cursor)]

    # -- control constructs --

    def _build_if(self, stmt: ast.If, cursor: list[int]) -> list[int]:
        test = self._new(stmt, cursor)
        exits = self.seq(stmt.body, [test])
        if stmt.orelse:
            exits += self.seq(stmt.orelse, [test])
        else:
            exits.append(test)
        return exits

    @staticmethod
    def _const_true(expr: ast.AST) -> bool:
        return isinstance(expr, ast.Constant) and bool(expr.value)

    def _build_while(self, stmt: ast.While,
                     cursor: list[int]) -> list[int]:
        header = self._new(stmt, cursor)
        breaks: list[int] = []
        self.loop_stack.append((header, breaks, len(self.fin_stack)))
        body_exits = self.seq(stmt.body, [header])
        self._wire(body_exits, header)  # back edges
        self.loop_stack.pop()
        if self._const_true(stmt.test):
            # `while True`: the only normal exits are breaks (orelse
            # is dead code then)
            return breaks
        exits = list(breaks)
        if stmt.orelse:
            exits += self.seq(stmt.orelse, [header])
        else:
            exits.append(header)
        return exits

    def _build_for(self, stmt: ast.AST,
                   cursor: list[int]) -> list[int]:
        # the header node is the iteration step: target rebinding and
        # __next__/__anext__ both happen here (async: suspension too)
        header = self._new(stmt, cursor)
        breaks: list[int] = []
        self.loop_stack.append((header, breaks, len(self.fin_stack)))
        body_exits = self.seq(stmt.body, [header])
        self._wire(body_exits, header)
        self.loop_stack.pop()
        exits = list(breaks)
        if stmt.orelse:
            exits += self.seq(stmt.orelse, [header])
        else:
            exits.append(header)
        return exits

    def _build_match(self, stmt: ast.Match,
                     cursor: list[int]) -> list[int]:
        subj = self._new(stmt, cursor)
        exits: list[int] = [subj]  # no case may match
        for case in stmt.cases:
            exits += self.seq(case.body, [subj])
        return exits

    def _build_try(self, stmt: ast.Try,
                   cursor: list[int]) -> list[int]:
        cfg = self.cfg
        outer = self._exc_target()
        fin_pad = cfg.add_node(K_FINPAD) if stmt.finalbody else None
        dispatch = cfg.add_node(K_DISPATCH) if stmt.handlers else None
        body_propagate = fin_pad if fin_pad is not None else outer

        if fin_pad is not None:
            self.fin_stack.append(fin_pad)

        # body: exceptions go to the handler dispatch (else straight to
        # the finally/outer frame)
        self.exc_stack.append(
            dispatch if dispatch is not None else body_propagate)
        body_exits = self.seq(stmt.body, list(cursor))
        self.exc_stack.pop()

        # orelse runs after a clean body and is NOT covered by the
        # handlers — its exceptions skip them (but do run finally)
        if stmt.orelse:
            self.exc_stack.append(body_propagate)
            body_exits = self.seq(stmt.orelse, body_exits)
            self.exc_stack.pop()

        handler_exits: list[int] = []
        if dispatch is not None:
            # an exception the handler list does not match propagates —
            # unless some handler is a catch-all (`except:` / `except
            # BaseException`; Exception does NOT qualify, it misses
            # CancelledError — the distinction this family exists for)
            if not any(_catches_everything(h) for h in stmt.handlers):
                cfg.exc[dispatch].add(body_propagate)
            for handler in stmt.handlers:
                h = cfg.add_node(K_HANDLER, handler)
                cfg.flow[dispatch].add(h)
                self.exc_stack.append(body_propagate)
                handler_exits += self.seq(handler.body, [h])
                self.exc_stack.pop()

        if fin_pad is not None:
            self.fin_stack.pop()
            self._wire(body_exits + handler_exits, fin_pad)
            self.exc_stack.append(outer)
            fin_exits = self.seq(stmt.finalbody, [fin_pad])
            self.exc_stack.pop()
            # the finally block may be completing an exceptional path:
            # its exits also propagate outward
            for e in fin_exits:
                cfg.exc[e].add(outer)
            return fin_exits
        return body_exits + handler_exits


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one ``def``/``async def`` body (lambdas have no
    statements — callers skip them)."""
    b = _Builder()
    exits = b.seq(fn.body, [b.cfg.entry])
    b._wire(exits, b.cfg.exit)
    return b.cfg


def dataflow(cfg: CFG, gen: Sequence[frozenset],
             kill: Sequence[frozenset], *, must: bool = False,
             init: frozenset = frozenset()) -> list[Optional[frozenset]]:
    """Forward gen/kill analysis to fixpoint; returns IN per node.

    *may* (default): union meet, unreachable nodes hold the empty set.
    *must*: intersection meet, unreachable nodes hold ``None`` (TOP).
    Edge transfer: flow edges carry ``(IN - kill) | gen``; exc edges
    carry ``IN - kill`` (pre-state with kills — see module docstring).
    ``init`` seeds the entry node (CB405 uses it for entered-metered
    frames)."""
    n = cfg.n_nodes
    preds = cfg.preds()
    inn: list[Optional[frozenset]] = \
        [None if must else frozenset()] * n
    inn[cfg.entry] = init
    changed = True
    while changed:
        changed = False
        for node in range(n):
            if node == cfg.entry:
                continue
            acc: Optional[frozenset] = None
            for pred, is_exc in preds[node]:
                pin = inn[pred]
                if pin is None:
                    continue  # TOP / not yet reached
                out = pin - kill[pred]
                if not is_exc:
                    out = out | gen[pred]
                if acc is None:
                    acc = out
                elif must:
                    acc = acc & out
                else:
                    acc = acc | out
            if acc is None:
                continue
            if acc != inn[node]:
                inn[node] = acc
                changed = True
    return inn
