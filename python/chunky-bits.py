#!/usr/bin/env python3
"""Read-only chunky-bits file-reference decoder (pyyaml is the only dep).

Interop role (cf. the reference repo's python/ decoder): given a
file-reference YAML/JSON document, stream the file it describes to stdout
by concatenating the *data* chunks in order and truncating to the recorded
length.  Only the first location of each chunk is consulted and there is no
erasure reconstruction — degraded files need the full CLI
(``chunky-bits cat @#<ref>``).  Works on references written by this
framework or by the original Rust implementation.
"""

from __future__ import annotations

import hashlib
import sys
import urllib.request

import yaml


def fetch(location: str) -> bytes:
    if location.startswith(("http://", "https://")):
        with urllib.request.urlopen(location) as resp:
            return resp.read()
    if "://" in location:
        raise ValueError(f"unsupported location scheme: {location}")
    with open(location, "rb") as f:
        return f.read()


def decode(ref_path: str, out) -> int:
    with open(ref_path) as f:
        ref = yaml.safe_load(f)

    remaining = ref.get("length")
    status = 0
    for part in ref.get("parts", []):
        for chunk in part.get("data", []):
            locations = chunk.get("locations") or []
            if not locations:
                print(f"chunk {chunk.get('sha256')} has no locations",
                      file=sys.stderr)
                return 1
            payload = fetch(locations[0])
            want = chunk.get("sha256")
            got = hashlib.sha256(payload).hexdigest()
            if want != got:
                print(f"hash mismatch at {locations[0]}: {want} != {got}",
                      file=sys.stderr)
                status = 1
            if remaining is not None:
                payload = payload[:remaining]
                remaining -= len(payload)
            out.write(payload)
    return status


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: chunky-bits.py <file-reference>", file=sys.stderr)
        return 2
    return decode(sys.argv[1], sys.stdout.buffer)


if __name__ == "__main__":
    sys.exit(main())
