"""On-chip A/B: standard fused kernel vs field-multiplexed packed kernel.

Shares bench.py's marginal-cost method (per-iteration device time of the
transform inside a fori_loop, differenced across loop lengths so dispatch
overhead and hoistable work cancel) so numbers are comparable with the
recorded bench figures.
"""
import sys

import numpy as np

import jax.numpy as jnp

from bench import marginal_seconds
from chunky_bits_tpu.ops import matrix
from chunky_bits_tpu.ops.pallas_kernels import (
    _build_kernel, _build_packed_kernel, bit_matrix_bitmajor)

d, p = 10, 4
batch, size = 128, 1 << 20
iters = 10

enc = matrix.build_encode_matrix(d, p)[d:]
m2 = jnp.asarray(bit_matrix_bitmajor(enc).astype(np.int8))
rng = np.random.default_rng(0)
data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
x = jnp.asarray(data)

xor_cost = marginal_seconds(lambda y: y, x, iters)
if xor_cost < 0:
    sys.exit("xor baseline did not scale linearly; rerun")
print(f"xor pass: {xor_cost*1e3:.2f} ms")


def gibps(secs):
    if secs <= xor_cost:
        return 0.0
    return batch * d * size / (secs - xor_cost) / (1 << 30)


# correctness gate on-chip: every config must match the standard kernel
std_ref = _build_kernel(p, d, 8192, 1, False)
want = np.asarray(std_ref(m2, x[:4, :, :65536]))

configs = [
    ("std", 32768, 2, _build_kernel(p, d, 32768, 2, False)),
    ("packed", 16384, 2, _build_packed_kernel(p, d, 16384, 2, False)),
    ("packed", 32768, 2, _build_packed_kernel(p, d, 32768, 2, False)),
    ("packed", 65536, 2, _build_packed_kernel(p, d, 65536, 2, False)),
    ("packed", 32768, 4, _build_packed_kernel(p, d, 32768, 4, False)),
]

failed = False
for name, tile, bblock, fn in configs:
    try:
        got = np.asarray(fn(m2, x[:4, :, :65536]))
    except Exception as err:  # e.g. VMEM overflow at the big tile
        print(f"{name} tile={tile} bblock={bblock}: COMPILE/RUN FAIL "
              f"({type(err).__name__})")
        failed = True
        continue
    if not np.array_equal(want, got):
        print(f"{name} tile={tile} bblock={bblock}: IDENTITY FAIL")
        failed = True
        continue
    t = marginal_seconds(lambda y, fn=fn: fn(m2, y), x, iters)
    if t < 0:
        print(f"{name:7s} tile={tile:6d} bblock={bblock}: non-linear "
              f"scaling, no measurement")
        continue
    print(f"{name:7s} tile={tile:6d} bblock={bblock}: {gibps(t):6.1f} GiB/s"
          f"  ({(t - xor_cost)*1e3:.2f} ms marginal)")

if failed:
    sys.exit(1)
