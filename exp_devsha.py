"""On-chip A/B: device-side SHA-256 vs host SHA for shard hashing.

Decides whether $CHUNKY_BITS_TPU_DEVICE_SHA should default on: the
device kernel wins if its marginal hashing rate beats the host engine's
(SHA-NI x cores — ~0.9 GiB/s/core here), because host SHA is the
measured pipeline ceiling while the chip idles post-encode (VERDICT r4
item 2; the reference hashes on CPU, src/file/file_part.rs:185).

Three numbers, all by bench.py's marginal method where applicable:
  1. device SHA alone over [N, 1 MiB] shard rows;
  2. fused encode+hash dispatch (parity + digests, one transfer) vs the
     plain parity dispatch — the marginal cost of in-dispatch hashing;
  3. host engine on the same rows (wall clock, it's synchronous).
Exits 1 on any digest mismatch vs hashlib.
"""
import hashlib
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from bench import marginal_seconds
from chunky_bits_tpu.ops import matrix
from chunky_bits_tpu.ops.sha256_jax import make_sha256_aligned

d, p = 10, 4
SMOKE = "--smoke" in sys.argv
if SMOKE:  # CPU-sized shapes: exercises every code path, numbers
    batch, size, iters = 2, 1 << 16, 2  # meaningless
else:
    batch, size, iters = 64, 1 << 20, 6

rng = np.random.default_rng(0)
data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)

# --- correctness gate on-chip ---------------------------------------
sha_small = jax.jit(make_sha256_aligned(size))
rows_small = data[:2].reshape(2 * d, size)
got = np.asarray(sha_small(jnp.asarray(rows_small)))
want = np.stack([np.frombuffer(hashlib.sha256(r.tobytes()).digest(),
                               dtype=np.uint8) for r in rows_small])
if not np.array_equal(got, want):
    print("device SHA digest mismatch vs hashlib ON CHIP", flush=True)
    sys.exit(1)
print("on-chip digest identity: OK", flush=True)

# --- 1. device SHA alone (marginal, [B*d, S] rows as [B', 1, S]) ----
# marginal_seconds wants [B, K, S]; present rows as [B*d, 1, S]
rows = data.reshape(batch * d, 1, size)
x = jnp.asarray(rows)
xor_cost = marginal_seconds(lambda y: y, x, iters)
if xor_cost < 0:
    if not SMOKE:
        sys.exit("xor baseline did not scale linearly; rerun")
    xor_cost = 0.0  # smoke: shapes too small to measure, keep going
sha_fn = make_sha256_aligned(size)
# marginal_seconds samples the body output as [B, _, :]: present the
# [N, 32] digests as [N, 1, 32]
t = marginal_seconds(lambda y: sha_fn(y[:, 0, :])[:, None, :], x, iters)
dev_gibps = (rows.nbytes / (t - xor_cost) / (1 << 30)
             if 0 < xor_cost < t else 0.0)
print(f"device SHA alone: {dev_gibps:.2f} GiB/s "
      f"({(t - xor_cost) * 1e3:.1f} ms marginal)", flush=True)

# --- 2. fused encode+hash vs plain encode ---------------------------
from chunky_bits_tpu.ops.jax_backend import JaxBackend
from chunky_bits_tpu.ops.pallas_kernels import apply_matrix_pallas

be = JaxBackend()
enc = matrix.build_encode_matrix(d, p)
parity_rows = enc[d:]
fused = be._fused_encode_hash_fn(parity_rows, size, interpret=SMOKE)
x3 = jnp.asarray(data)
t_plain = marginal_seconds(
    lambda y: apply_matrix_pallas(parity_rows, y, interpret=SMOKE),
    x3, iters)
def _fused_sample(y):
    # fold the digests into the consumed output: sampling only parity
    # would let XLA dead-code-eliminate the whole SHA computation and
    # the "hash overhead" would read ~0
    par, dig = fused(y)
    return par.at[:, :, :32].set(par[:, :, :32] ^ dig[:, :par.shape[1]])


t_fused = marginal_seconds(_fused_sample, x3, iters)
xor3 = marginal_seconds(lambda y: y, x3, iters)
plain = t_plain - xor3
fusedm = t_fused - xor3
if xor3 > 0 and plain > 0 and fusedm > 0:
    print(f"plain encode: {data.nbytes / plain / (1 << 30):.1f} GiB/s | "
          f"fused encode+hash: {data.nbytes / fusedm / (1 << 30):.1f} "
          f"GiB/s | hash overhead: {(fusedm - plain) * 1e3:.1f} ms "
          f"({(fusedm / plain - 1) * 100:.0f}%)", flush=True)

# --- 3. host engine on the same rows --------------------------------
from chunky_bits_tpu.ops.backend import row_hasher

hash_rows = row_hasher()
flat = data.reshape(batch * d, size)
out = np.empty((flat.shape[0], 32), dtype=np.uint8)
hash_rows(flat.reshape(batch, d, size),
          out.reshape(batch, d, 32))  # warm
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    hash_rows(flat.reshape(batch, d, size), out.reshape(batch, d, 32))
    best = min(best, time.perf_counter() - t0)
host_gibps = flat.nbytes / best / (1 << 30)
print(f"host SHA engine: {host_gibps:.2f} GiB/s (this host)", flush=True)

print(f"VERDICT: device {'WINS' if dev_gibps > host_gibps else 'loses'}"
      f" ({dev_gibps:.2f} vs {host_gibps:.2f} GiB/s on this host; "
      f"multiply host by its core count for other hosts)", flush=True)
