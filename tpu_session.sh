#!/bin/bash
# The round-5 TPU session: everything that needs the real chip, in
# priority order (VERDICT r4 items 1, 4, 8 + the device-SHA A/B).
# Run when the tunnel is up; each step logs to tpu_session/<step>.log
# and a failed step doesn't stop the rest.  Re-runnable.
set -u
mkdir -p tpu_session
run() {
  local name=$1; shift
  echo "=== $name: $* ==="
  timeout "${STEP_TIMEOUT:-1800}" "$@" 2>&1 | tee "tpu_session/$name.log"
  echo "=== $name rc=$? ==="
}

# 1. the round's device record: d10p4 encode/decode + wide d16p8
run bench python bench.py

# 2. packed-kernel A/B -> decides _PACKED_DEFAULT (flip or delete)
run exp_packed python exp_packed.py

# 3. device-SHA A/B -> decides CHUNKY_BITS_TPU_DEVICE_SHA default
run exp_devsha python exp_devsha.py

# 4. wide-stripe tp kernels compiled on one chip (closes the last
#    interpret-only gap)
run exp_tp python exp_tp.py

# 5. config 2/3 pipeline numbers on the device backend
run cfg2 python bench.py --config 2 --gib 0.5
run cfg3 env CHUNKY_BITS_TPU_BACKEND=jax python bench.py --config 3

echo "=== session done; logs in tpu_session/ ==="
